// AMQP 0-9-1 queue-client driver: the framework's native layer.
//
// Re-implements the behavior of the reference's Java driver
// (/root/reference/rabbitmq/src/main/java/com/rabbitmq/jepsen/Utils.java)
// as a C++ library with a C ABI for Python ctypes:
//
// - connection with a bounded retry loop, automatic recovery OFF — the test
//   controls reconnection explicitly (Utils.java:289-317)
// - lazy per-client initialization; once-guarded quorum-queue declaration
//   (x-queue-type=quorum, optional initial group size, optional dead-letter
//   topology with at-least-once strategy / reject-publish overflow / 1s TTL)
//   followed by a purge (Utils.java:319-374)
// - enqueue = persistent+mandatory publish + wait-for-confirms with timeout
//   (Utils.java:376-385)
// - dequeue with a hard deadline: polling basic.get+ack (Utils.java:563-630)
//   or an async consumer (QoS 1) feeding an in-memory deque
//   (Utils.java:473-561); "mixed" alternates per client (Utils.java:88-94)
// - drain choreography: global once-latch; close ALL clients so un-acked
//   messages requeue, wait, then connect to EVERY known host and
//   basic.get-loop the queue (and dead-letter queue) until empty, acking
//   each message (Utils.java:413-470)
//
// Concurrency design: one reader thread per connection routes inbound
// frames — publisher confirms update a seqno watermark, deliveries feed the
// consumer deque, synchronous method responses land in an RPC mailbox; all
// guarded by one mutex + condvars per connection.

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdarg>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "amqp_wire.hpp"

namespace {

using Clock = std::chrono::steady_clock;
using std::chrono::milliseconds;

constexpr const char* QUEUE_NAME = "jepsen.queue";
constexpr const char* DLQ_NAME = "jepsen.queue.dead.letter";
constexpr int MESSAGE_TTL_MS = 1000;  // Utils.java:55

int g_log_enabled = 1;

void logf(const char* fmt, ...) {
  if (!g_log_enabled) return;
  va_list ap;
  va_start(ap, fmt);
  std::fprintf(stderr, "[amqp-driver] ");
  std::vfprintf(stderr, fmt, ap);
  std::fprintf(stderr, "\n");
  va_end(ap);
}

// ---------------------------------------------------------------------------
// TCP socket
// ---------------------------------------------------------------------------

class Socket {
 public:
  ~Socket() { close_fd(); }
  bool connect_to(const std::string& host, int port, int timeout_ms) {
    close_fd();
    struct addrinfo hints = {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0)
      return false;
    bool ok = false;
    for (auto* ai = res; ai; ai = ai->ai_next) {
      fd_ = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd_ < 0) continue;
      struct timeval tv = {timeout_ms / 1000, (timeout_ms % 1000) * 1000};
      setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      if (connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) {
        ok = true;
        break;
      }
      close_fd();
    }
    freeaddrinfo(res);
    return ok;
  }
  void set_recv_timeout(int ms) {
    if (fd_ < 0) return;
    struct timeval tv = {ms / 1000, (ms % 1000) * 1000};
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }
  bool send_all(const uint8_t* p, size_t n) {
    while (n) {
      ssize_t k = send(fd_, p, n, MSG_NOSIGNAL);
      if (k <= 0) return false;
      p += k;
      n -= k;
    }
    return true;
  }
  // 1 = got all, 0 = timeout, -1 = closed/error
  int recv_all(uint8_t* p, size_t n) {
    while (n) {
      ssize_t k = recv(fd_, p, n, 0);
      if (k == 0) return -1;
      if (k < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
        return -1;
      }
      p += k;
      n -= k;
    }
    return 1;
  }
  void close_fd() {
    if (fd_ >= 0) {
      ::shutdown(fd_, SHUT_RDWR);
      ::close(fd_);
      fd_ = -1;
    }
  }
  bool valid() const { return fd_ >= 0; }

 private:
  int fd_ = -1;
};

// ---------------------------------------------------------------------------
// Connection: handshake + reader thread + RPC mailbox
// ---------------------------------------------------------------------------

struct Delivery {
  uint64_t tag;
  int32_t value;
  int64_t offset = -1;  // stream log offset (x-stream-offset header)
};

class Connection {
 public:
  Connection(std::string host, int port, std::string user, std::string pass)
      : host_(std::move(host)), port_(port), user_(std::move(user)),
        pass_(std::move(pass)) {}

  ~Connection() { close(); }

  bool open(int timeout_ms) {
    std::lock_guard<std::mutex> lk(write_mu_);
    if (!sock_.connect_to(host_, port_, timeout_ms)) return false;
    static const uint8_t proto[8] = {'A', 'M', 'Q', 'P', 0, 0, 9, 1};
    if (!sock_.send_all(proto, 8)) return false;
    try {
      // Connection.Start / Start-Ok (PLAIN)
      amqp::Frame f = read_frame_sync();
      expect_method(f, amqp::CLS_CONNECTION, amqp::M_CONN_START);
      {
        auto w = amqp::method_writer(amqp::CLS_CONNECTION,
                                     amqp::M_CONN_START_OK);
        amqp::Table props;
        props.put_str("product", "jepsen-tpu-driver");
        props.serialize(w);
        w.shortstr("PLAIN");
        std::string resp;
        resp.push_back('\0');
        resp += user_;
        resp.push_back('\0');
        resp += pass_;
        w.longstr(resp);
        w.shortstr("en_US");
        send_frame_locked(amqp::FRAME_METHOD, 0, w.buf);
      }
      // Tune / Tune-Ok (heartbeat 0: the test layer owns liveness)
      f = read_frame_sync();
      expect_method(f, amqp::CLS_CONNECTION, amqp::M_CONN_TUNE);
      {
        amqp::Reader r(f.payload.data(), f.payload.size());
        r.u16();
        r.u16();
        uint16_t channel_max = r.u16();
        uint32_t frame_max = r.u32();
        (void)channel_max;
        frame_max_ = frame_max ? std::min(frame_max, 131072u) : 131072u;
        auto w =
            amqp::method_writer(amqp::CLS_CONNECTION, amqp::M_CONN_TUNE_OK);
        w.u16(2047);
        w.u32(frame_max_);
        w.u16(0);
        send_frame_locked(amqp::FRAME_METHOD, 0, w.buf);
      }
      // Open / Open-Ok
      {
        auto w = amqp::method_writer(amqp::CLS_CONNECTION, amqp::M_CONN_OPEN);
        w.shortstr("/");
        w.shortstr("");
        w.u8(0);
        send_frame_locked(amqp::FRAME_METHOD, 0, w.buf);
      }
      f = read_frame_sync();
      expect_method(f, amqp::CLS_CONNECTION, amqp::M_CONN_OPEN_OK);
      // Channel.Open / Open-Ok
      {
        auto w = amqp::method_writer(amqp::CLS_CHANNEL, amqp::M_CH_OPEN);
        w.shortstr("");
        send_frame_locked(amqp::FRAME_METHOD, 1, w.buf);
      }
      f = read_frame_sync();
      expect_method(f, amqp::CLS_CHANNEL, amqp::M_CH_OPEN_OK);
    } catch (const std::exception& e) {
      logf("handshake with %s failed: %s", host_.c_str(), e.what());
      sock_.close_fd();
      return false;
    }
    sock_.set_recv_timeout(250);  // reader thread poll granularity
    closed_ = false;
    reader_ = std::thread([this] { reader_loop(); });
    return true;
  }

  void close() {
    {
      std::lock_guard<std::mutex> lk(write_mu_);
      if (!closed_ && sock_.valid()) {
        try {
          auto w =
              amqp::method_writer(amqp::CLS_CONNECTION, amqp::M_CONN_CLOSE);
          w.u16(200);
          w.shortstr("bye");
          w.u16(0);
          w.u16(0);
          send_frame_locked(amqp::FRAME_METHOD, 0, w.buf);
        } catch (...) {
        }
      }
      closed_ = true;
      sock_.close_fd();
    }
    signal_state();
    if (reader_.joinable()) reader_.join();
  }

  bool alive() { return !closed_ && !broken_; }

  // ---- RPC: send a method on channel 1, wait for (cls, mth) ------------
  bool rpc(const amqp::Writer& w, uint16_t cls, uint16_t mth,
           amqp::Frame* out, int timeout_ms, bool* sent_out = nullptr) {
    std::unique_lock<std::mutex> lk(state_mu_);
    rpc_expect_cls_ = cls;
    rpc_expect_mth_ = mth;
    rpc_have_ = false;
    lk.unlock();
    {
      std::lock_guard<std::mutex> wlk(write_mu_);
      if (closed_ || broken_) return false;
      if (!send_frame_locked(amqp::FRAME_METHOD, 1, w.buf)) return false;
      if (sent_out) *sent_out = true;
    }
    lk.lock();
    bool ok = state_cv_.wait_for(lk, milliseconds(timeout_ms), [&] {
      return rpc_have_ || broken_ || closed_;
    });
    if (!ok || !rpc_have_) return false;
    if (out) *out = rpc_frame_;
    rpc_expect_cls_ = 0;
    return true;
  }

  // ---- publish + confirm -------------------------------------------------
  void enable_confirms() {
    if (confirms_on_) return;  // idempotent: confirm mode is sticky
    auto w = amqp::method_writer(amqp::CLS_CONFIRM, amqp::M_CF_SELECT);
    w.u8(0);
    amqp::Frame f;
    if (!rpc(w, amqp::CLS_CONFIRM, amqp::M_CF_SELECT_OK, &f, 5000))
      throw std::runtime_error("confirm.select failed");
    confirms_on_ = true;
  }

  // enable_confirms without the throw: false = connection unusable
  bool ensure_confirms() {
    try {
      enable_confirms();
      return true;
    } catch (const std::exception&) {
      return false;
    }
  }

  // ---- tx class (AMQP 0-9-1 transactions) --------------------------------
  bool tx_select(int timeout_ms = 5000) {
    auto w = amqp::method_writer(amqp::CLS_TX, amqp::M_TX_SELECT);
    amqp::Frame f;
    return rpc(w, amqp::CLS_TX, amqp::M_TX_SELECT_OK, &f, timeout_ms);
  }

  // 1 committed, -1 outcome unknown (commit reached the wire but no
  // commit-ok arrived — timeout OR the connection broke after the send),
  // -2 determinate failure (the commit never left this process)
  int tx_commit(int timeout_ms) {
    auto w = amqp::method_writer(amqp::CLS_TX, amqp::M_TX_COMMIT);
    amqp::Frame f;
    {
      std::lock_guard<std::mutex> slk(state_mu_);
      if (closed_ || broken_) return -2;
    }
    bool sent = false;
    if (rpc(w, amqp::CLS_TX, amqp::M_TX_COMMIT_OK, &f, timeout_ms, &sent))
      return 1;
    return sent ? -1 : -2;
  }

  bool tx_rollback(int timeout_ms = 5000) {
    auto w = amqp::method_writer(amqp::CLS_TX, amqp::M_TX_ROLLBACK);
    amqp::Frame f;
    return rpc(w, amqp::CLS_TX, amqp::M_TX_ROLLBACK_OK, &f, timeout_ms);
  }

  // fire-and-forget publish (tx mode: outcome decided at tx.commit)
  bool publish_plain(const std::string& queue, int32_t value) {
    std::lock_guard<std::mutex> wlk(write_mu_);
    if (closed_ || broken_) return false;
    std::string body = std::to_string(value);
    auto m = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_PUBLISH);
    m.u16(0);
    m.shortstr("");
    m.shortstr(queue);
    m.u8(0);  // not mandatory: tx routing errors surface at commit/close
    amqp::Writer out;
    amqp::serialize_frame(out, amqp::FRAME_METHOD, 1, m.buf);
    amqp::serialize_frame(out, amqp::FRAME_HEADER, 1,
                          amqp::content_header(body.size()));
    std::vector<uint8_t> bodyv(body.begin(), body.end());
    amqp::serialize_frame(out, amqp::FRAME_BODY, 1, bodyv);
    if (!sock_.send_all(out.buf.data(), out.buf.size())) {
      broken_ = true;
      return false;
    }
    return true;
  }

  // 1 confirmed, 0 nacked/returned, -1 timeout, -2 connection error
  int publish_confirm(const std::string& queue, int32_t value,
                      int timeout_ms) {
    return publish_confirm_props(queue, std::to_string(value), nullptr,
                                 timeout_ms);
  }

  // publish_confirm with caller-supplied content-header properties
  // (property-flags onward); nullptr = the default persistent header.
  // The codec-fuzz surface publishes arbitrary header tables this way.
  int publish_confirm_props(const std::string& queue, const std::string& body,
                            const std::vector<uint8_t>* props,
                            int timeout_ms) {
    uint64_t seq;
    {
      std::lock_guard<std::mutex> wlk(write_mu_);
      if (closed_ || broken_) return -2;
      seq = ++publish_seq_;
      auto m = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_PUBLISH);
      m.u16(0);
      m.shortstr("");       // default exchange
      m.shortstr(queue);    // routing key = queue
      m.u8(1);              // mandatory
      amqp::Writer out;
      amqp::serialize_frame(out, amqp::FRAME_METHOD, 1, m.buf);
      std::vector<uint8_t> header;
      if (props) {
        amqp::Writer h;
        h.u16(amqp::CLS_BASIC);
        h.u16(0);
        h.u64(body.size());
        h.bytes(props->data(), props->size());
        header = h.buf;
      } else {
        header = amqp::content_header(body.size());
      }
      amqp::serialize_frame(out, amqp::FRAME_HEADER, 1, header);
      std::vector<uint8_t> bodyv(body.begin(), body.end());
      amqp::serialize_frame(out, amqp::FRAME_BODY, 1, bodyv);
      if (!sock_.send_all(out.buf.data(), out.buf.size())) {
        broken_ = true;
        return -2;
      }
    }
    std::unique_lock<std::mutex> lk(state_mu_);
    bool done = state_cv_.wait_for(lk, milliseconds(timeout_ms), [&] {
      return confirmed_up_to_ >= seq || nacked_.count(seq) ||
             returned_since_.load() > 0 || broken_ || closed_;
    });
    if (broken_ || closed_) return -2;
    if (!done) return -1;
    if (nacked_.count(seq)) {
      nacked_.erase(seq);
      return 0;
    }
    if (returned_since_.load() > 0) {
      returned_since_ = 0;
      return 0;  // mandatory return: unroutable
    }
    return 1;
  }

  // ---- basic.get ---------------------------------------------------------
  // 1 = message (value+tag set; *fence_out = x-fence-token header or -1),
  // 0 = empty, -1 = timeout, -2 = error
  int basic_get(const std::string& queue, int32_t* value, uint64_t* tag,
                int timeout_ms, int64_t* fence_out = nullptr) {
    auto w = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_GET);
    w.u16(0);
    w.shortstr(queue);
    w.u8(0);  // manual ack
    std::unique_lock<std::mutex> lk(state_mu_);
    get_result_pending_ = true;
    get_have_ = 0;
    lk.unlock();
    {
      std::lock_guard<std::mutex> wlk(write_mu_);
      if (closed_ || broken_) return -2;
      if (!send_frame_locked(amqp::FRAME_METHOD, 1, w.buf)) return -2;
    }
    lk.lock();
    bool done = state_cv_.wait_for(lk, milliseconds(timeout_ms), [&] {
      return get_have_ != 0 || broken_ || closed_;
    });
    get_result_pending_ = false;
    if (broken_ || closed_) return -2;
    if (!done) return -1;
    if (get_have_ == 2) return 0;  // get-empty
    *value = get_value_;
    *tag = get_tag_;
    if (fence_out) *fence_out = get_fence_;
    return 1;
  }

  bool basic_ack(uint64_t tag) {
    auto w = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_ACK);
    w.u64(tag);
    w.u8(0);
    std::lock_guard<std::mutex> wlk(write_mu_);
    if (closed_ || broken_) return false;
    return send_frame_locked(amqp::FRAME_METHOD, 1, w.buf);
  }

  bool basic_reject_requeue(uint64_t tag) {
    auto w = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_REJECT);
    w.u64(tag);
    w.u8(1);  // requeue
    std::lock_guard<std::mutex> wlk(write_mu_);
    if (closed_ || broken_) return false;
    return send_frame_locked(amqp::FRAME_METHOD, 1, w.buf);
  }

  // ---- consumer ----------------------------------------------------------
  bool start_consumer(const std::string& queue, int prefetch = 1,
                      const amqp::Table* args = nullptr,
                      const std::string& tag = "") {
    {
      auto w = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_QOS);
      w.u32(0);
      w.u16(static_cast<uint16_t>(prefetch));  // (Utils.java:540)
      w.u8(0);
      amqp::Frame f;
      if (!rpc(w, amqp::CLS_BASIC, amqp::M_B_QOS_OK, &f, 5000)) return false;
    }
    auto w = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_CONSUME);
    w.u16(0);
    w.shortstr(queue);
    w.shortstr(tag);  // empty = server-assigned
    w.u8(0);          // no-local=0 no-ack=0 exclusive=0 no-wait=0
    if (args)
      args->serialize(w);
    else
      amqp::Table().serialize(w);
    amqp::Frame f;
    return rpc(w, amqp::CLS_BASIC, amqp::M_B_CONSUME_OK, &f, 5000);
  }

  bool cancel_consumer(const std::string& tag) {
    auto w = amqp::method_writer(amqp::CLS_BASIC, amqp::M_B_CANCEL);
    w.shortstr(tag);
    w.u8(0);  // no-wait=0
    amqp::Frame f;
    return rpc(w, amqp::CLS_BASIC, amqp::M_B_CANCEL_OK, &f, 5000);
  }

  // pop one delivery; 1 = got, -1 = timeout, -2 = error
  int pop_delivery(Delivery* d, int timeout_ms) {
    std::unique_lock<std::mutex> lk(state_mu_);
    bool ok = state_cv_.wait_for(lk, milliseconds(timeout_ms), [&] {
      return !deliveries_.empty() || broken_ || closed_;
    });
    if (!deliveries_.empty()) {
      *d = deliveries_.front();
      deliveries_.pop_front();
      return 1;
    }
    if (broken_ || closed_) return -2;
    (void)ok;
    return -1;
  }

  // ---- queue management --------------------------------------------------
  bool declare_queue(const std::string& queue, const amqp::Table& args) {
    auto w = amqp::method_writer(amqp::CLS_QUEUE, amqp::M_Q_DECLARE);
    w.u16(0);
    w.shortstr(queue);
    w.u8(0x02);  // durable only
    args.serialize(w);
    amqp::Frame f;
    return rpc(w, amqp::CLS_QUEUE, amqp::M_Q_DECLARE_OK, &f, 10000);
  }

  bool purge_queue(const std::string& queue) {
    auto w = amqp::method_writer(amqp::CLS_QUEUE, amqp::M_Q_PURGE);
    w.u16(0);
    w.shortstr(queue);
    w.u8(0);
    amqp::Frame f;
    return rpc(w, amqp::CLS_QUEUE, amqp::M_Q_PURGE_OK, &f, 10000);
  }

  const std::string& host() const { return host_; }

 private:
  // store-flag → empty state_mu_ critical section → notify: guarantees a
  // waiter that checked the predicate before the store sees the wakeup
  void signal_state() {
    { std::lock_guard<std::mutex> s(state_mu_); }
    state_cv_.notify_all();
  }

  bool send_frame_locked(uint8_t type, uint16_t ch,
                         const std::vector<uint8_t>& payload) {
    amqp::Writer out;
    amqp::serialize_frame(out, type, ch, payload);
    if (!sock_.send_all(out.buf.data(), out.buf.size())) {
      broken_ = true;
      signal_state();
      return false;
    }
    return true;
  }

  // blocking single-frame read (handshake only, before reader starts)
  amqp::Frame read_frame_sync() {
    amqp::Frame f;
    uint8_t hdr[7];
    int r = sock_.recv_all(hdr, 7);
    if (r != 1) throw std::runtime_error("read frame header failed");
    f.type = hdr[0];
    f.channel = (uint16_t(hdr[1]) << 8) | hdr[2];
    uint32_t size = 0;
    for (int i = 3; i < 7; ++i) size = (size << 8) | hdr[i];
    if (size > 16 * 1024 * 1024) throw std::runtime_error("frame too large");
    f.payload.resize(size);
    if (size && sock_.recv_all(f.payload.data(), size) != 1)
      throw std::runtime_error("read frame payload failed");
    uint8_t end;
    if (sock_.recv_all(&end, 1) != 1 || end != amqp::FRAME_END)
      throw std::runtime_error("bad frame end");
    return f;
  }

  static void expect_method(const amqp::Frame& f, uint16_t cls,
                            uint16_t mth) {
    if (f.type != amqp::FRAME_METHOD)
      throw std::runtime_error("expected method frame");
    amqp::Reader r(f.payload.data(), f.payload.size());
    uint16_t c = r.u16(), m = r.u16();
    if (c != cls || m != mth)
      throw std::runtime_error("unexpected method " + std::to_string(c) +
                               "." + std::to_string(m));
  }

  enum class ContentFor { NONE, DELIVER, GET };

  void reader_loop() {
    // pending content state (deliver / get-ok)
    ContentFor pending = ContentFor::NONE;
    uint64_t pending_tag = 0;
    int64_t pending_offset = -1;
    int64_t pending_fence = -1;
    std::string body_acc;
    uint64_t body_expected = 0;

    while (true) {
      {
        std::lock_guard<std::mutex> lk(state_mu_);
        if (closed_ || broken_) break;
      }
      amqp::Frame f;
      uint8_t hdr[7];
      int r = sock_.recv_all(hdr, 7);
      if (r == 0) continue;  // poll timeout
      if (r < 0) {
        mark_broken();
        break;
      }
      f.type = hdr[0];
      f.channel = (uint16_t(hdr[1]) << 8) | hdr[2];
      uint32_t size = 0;
      for (int i = 3; i < 7; ++i) size = (size << 8) | hdr[i];
      if (size > 16 * 1024 * 1024) {
        mark_broken();
        break;
      }
      f.payload.resize(size);
      if (size) {
        sock_.set_recv_timeout(5000);
        if (sock_.recv_all(f.payload.data(), size) != 1) {
          mark_broken();
          break;
        }
      }
      uint8_t end;
      if (sock_.recv_all(&end, 1) != 1 || end != amqp::FRAME_END) {
        mark_broken();
        break;
      }
      sock_.set_recv_timeout(250);

      try {
        if (f.type == amqp::FRAME_HEARTBEAT) {
          std::lock_guard<std::mutex> wlk(write_mu_);
          std::vector<uint8_t> empty;
          send_frame_locked(amqp::FRAME_HEARTBEAT, 0, empty);
          continue;
        }
        if (f.type == amqp::FRAME_HEADER) {
          amqp::Reader rd(f.payload.data(), f.payload.size());
          rd.u16();
          rd.u16();
          body_expected = rd.u64();
          body_acc.clear();
          pending_offset = amqp::header_stream_offset(f.payload);
          pending_fence = amqp::header_i64(f.payload, "x-fence-token");
          if (body_expected == 0) {
            finish_content(pending, pending_tag, "", pending_offset,
                           pending_fence);
            pending = ContentFor::NONE;
          }
          continue;
        }
        if (f.type == amqp::FRAME_BODY) {
          body_acc.append(reinterpret_cast<char*>(f.payload.data()),
                          f.payload.size());
          if (body_acc.size() >= body_expected) {
            finish_content(pending, pending_tag, body_acc, pending_offset,
                           pending_fence);
            pending = ContentFor::NONE;
          }
          continue;
        }
        // method frame
        amqp::Reader rd(f.payload.data(), f.payload.size());
        uint16_t cls = rd.u16(), mth = rd.u16();
        if (cls == amqp::CLS_BASIC && mth == amqp::M_B_ACK) {
          uint64_t tag = rd.u64();
          uint8_t multiple = rd.u8();
          std::lock_guard<std::mutex> lk(state_mu_);
          if (multiple)
            confirmed_up_to_ = std::max(confirmed_up_to_, tag);
          else if (tag == confirmed_up_to_ + 1)
            confirmed_up_to_ = tag;
          else
            acked_single_.insert(tag);
          while (acked_single_.count(confirmed_up_to_ + 1)) {
            acked_single_.erase(confirmed_up_to_ + 1);
            ++confirmed_up_to_;
          }
          state_cv_.notify_all();
        } else if (cls == amqp::CLS_BASIC && mth == amqp::M_B_NACK) {
          uint64_t tag = rd.u64();
          uint8_t bits = rd.u8();
          std::lock_guard<std::mutex> lk(state_mu_);
          if (bits & 1) {  // multiple
            for (uint64_t t = confirmed_up_to_ + 1; t <= tag; ++t)
              nacked_.insert(t);
            confirmed_up_to_ = std::max(confirmed_up_to_, tag);
          } else {
            nacked_.insert(tag);
          }
          state_cv_.notify_all();
        } else if (cls == amqp::CLS_BASIC && mth == amqp::M_B_RETURN) {
          returned_since_++;
          state_cv_.notify_all();
        } else if (cls == amqp::CLS_BASIC && mth == amqp::M_B_DELIVER) {
          rd.shortstr();              // consumer tag
          pending_tag = rd.u64();     // delivery tag
          pending = ContentFor::DELIVER;
        } else if (cls == amqp::CLS_BASIC && mth == amqp::M_B_GET_OK) {
          pending_tag = rd.u64();
          pending = ContentFor::GET;
        } else if (cls == amqp::CLS_BASIC && mth == amqp::M_B_GET_EMPTY) {
          std::lock_guard<std::mutex> lk(state_mu_);
          if (get_result_pending_) get_have_ = 2;
          state_cv_.notify_all();
        } else if (cls == amqp::CLS_CONNECTION &&
                   mth == amqp::M_CONN_CLOSE) {
          {
            std::lock_guard<std::mutex> wlk(write_mu_);
            auto w = amqp::method_writer(amqp::CLS_CONNECTION,
                                         amqp::M_CONN_CLOSE_OK);
            send_frame_locked(amqp::FRAME_METHOD, 0, w.buf);
          }
          mark_broken();
          break;
        } else if (cls == amqp::CLS_CHANNEL && mth == amqp::M_CH_CLOSE) {
          {
            std::lock_guard<std::mutex> wlk(write_mu_);
            auto w = amqp::method_writer(amqp::CLS_CHANNEL,
                                         amqp::M_CH_CLOSE_OK);
            send_frame_locked(amqp::FRAME_METHOD, 1, w.buf);
          }
          mark_broken();
          break;
        } else {
          // RPC response?
          std::lock_guard<std::mutex> lk(state_mu_);
          if (rpc_expect_cls_ == cls && rpc_expect_mth_ == mth) {
            rpc_frame_ = f;
            rpc_have_ = true;
            state_cv_.notify_all();
          }
          // anything else: ignore
        }
      } catch (const std::exception& e) {
        logf("reader error on %s: %s", host_.c_str(), e.what());
        mark_broken();
        break;
      }
    }
  }

  void finish_content(ContentFor pending_kind, uint64_t tag,
                      const std::string& body, int64_t offset = -1,
                      int64_t fence = -1) {
    int32_t value = -1;
    try {
      if (!body.empty()) value = std::stoi(body);
    } catch (...) {
      value = -1;
    }
    std::lock_guard<std::mutex> lk(state_mu_);
    if (pending_kind == ContentFor::DELIVER) {
      deliveries_.push_back({tag, value, offset});
    } else if (pending_kind == ContentFor::GET) {
      if (get_result_pending_) {
        get_value_ = value;
        get_tag_ = tag;
        get_fence_ = fence;
        get_have_ = 1;
      }
    }
    state_cv_.notify_all();
  }

  void mark_broken() {
    std::lock_guard<std::mutex> lk(state_mu_);
    broken_ = true;
    state_cv_.notify_all();
  }

  std::string host_;
  int port_;
  std::string user_, pass_;
  Socket sock_;
  uint32_t frame_max_ = 131072;
  std::thread reader_;

  std::mutex write_mu_;  // serializes socket writes
  std::mutex state_mu_;  // guards everything below
  std::condition_variable state_cv_;
  // closed_/broken_ are atomics: written under write_mu_ or state_mu_ but
  // read from cv predicates under state_mu_ — signal_state() pairs every
  // store with a state_mu_ acquire/release so waiters can't miss the wakeup
  std::atomic<bool> closed_{true};
  std::atomic<bool> broken_{false};

  // confirms
  bool confirms_on_ = false;
  uint64_t publish_seq_ = 0;
  uint64_t confirmed_up_to_ = 0;
  std::set<uint64_t> acked_single_;
  std::set<uint64_t> nacked_;
  std::atomic<int> returned_since_{0};

  // rpc mailbox
  uint16_t rpc_expect_cls_ = 0, rpc_expect_mth_ = 0;
  bool rpc_have_ = false;
  amqp::Frame rpc_frame_;

  // basic.get state
  bool get_result_pending_ = false;
  int get_have_ = 0;  // 1 = message, 2 = empty
  int32_t get_value_ = -1;
  uint64_t get_tag_ = 0;
  int64_t get_fence_ = -1;  // x-fence-token of the got message, -1 = none

  // consumer deque
  std::deque<Delivery> deliveries_;

 public:
  void clear_deliveries() {
    std::lock_guard<std::mutex> lk(state_mu_);
    deliveries_.clear();
  }
};

}  // namespace

// ===========================================================================
// Client layer + C ABI
// ===========================================================================

namespace {

struct ClientConfig {
  std::vector<std::string> hosts;  // every cluster node (drain visits all)
  std::string host;                // this client's node
  int port = 5672;
  std::string user = "guest", pass = "guest";
  int consumer_type = 0;  // 0 polling, 1 async, 2 resolved from mixed
  int quorum_group_size = 0;
  bool dead_letter = false;
  int connect_retry_ms = 30000;  // Utils.java:294-304
  bool fenced = false;  // lock client: fencing-token mode
};

class Client;
std::mutex g_registry_mu;
std::vector<Client*> g_clients;       // Utils.java CLIENTS (:256)
std::set<std::string> g_hosts;        // Utils.java HOSTS (:257)
std::atomic<int> g_mixed_counter{0};  // alternates consumer types (:88-94)
bool g_queues_declared = false;       // QUEUES_DECLARED latch (:259)
bool g_drained = false;               // DRAINED latch (:258)
bool g_drain_done = false;
std::vector<int32_t> g_drain_result;
std::condition_variable g_drain_cv;
int g_drain_wait_ms = 5000;  // redelivery settle time (Utils.java:427)

// "host[:port]" → (host, port).  Local multi-node clusters put every node
// on 127.0.0.1 with a distinct port, so node names may carry their own
// port which overrides the config default.  A non-numeric suffix is
// treated as part of the host, and an IPv6 literal (more than one ':',
// or bracketed) falls through whole to the config default port — rfind
// on "::1" would otherwise misparse host ":" port 1 (advisor r4).
std::pair<std::string, int> split_host_port(const std::string& h, int def) {
  if (!h.empty() && h[0] == '[') {  // [v6literal] or [v6literal]:port
    auto close = h.find(']');
    if (close == std::string::npos) return {h, def};  // malformed: as-is
    std::string host = h.substr(1, close - 1);
    if (close + 2 < h.size() && h[close + 1] == ':') {
      std::string port_s = h.substr(close + 2);
      if (port_s.find_first_not_of("0123456789") == std::string::npos)
        return {host, std::atoi(port_s.c_str())};
    }
    return {host, def};
  }
  if (std::count(h.begin(), h.end(), ':') > 1)
    return {h, def};  // bare IPv6 literal: no port suffix to split
  auto colon = h.rfind(':');
  if (colon == std::string::npos || colon + 1 >= h.size()) return {h, def};
  std::string port_s = h.substr(colon + 1);
  if (port_s.find_first_not_of("0123456789") != std::string::npos)
    return {h, def};
  return {h.substr(0, colon), std::atoi(port_s.c_str())};
}

// shared connect-retry loop (Utils.java:294-304): keep trying within the
// budget, 1 s between attempts; null when the budget runs out
std::shared_ptr<Connection> connect_with_retry(const ClientConfig& cfg,
                                               int budget_ms) {
  auto deadline = Clock::now() + milliseconds(budget_ms);
  auto hp = split_host_port(cfg.host, cfg.port);
  while (true) {
    // each attempt is clipped to the remaining budget (a 2 s budget must
    // not block 5 s in open), floor 250 ms so a dreg of budget still makes
    // one genuine attempt
    auto left = std::chrono::duration_cast<milliseconds>(deadline -
                                                         Clock::now())
                    .count();
    int attempt_ms =
        static_cast<int>(std::max<long long>(250, std::min<long long>(5000, left)));
    auto conn = std::make_shared<Connection>(hp.first, hp.second, cfg.user,
                                             cfg.pass);
    if (conn->open(attempt_ms)) return conn;
    if (Clock::now() + milliseconds(1000) >= deadline) break;
    std::this_thread::sleep_for(milliseconds(1000));
  }
  logf("connect to %s: retry budget exhausted", cfg.host.c_str());
  return nullptr;
}

class Client {
 public:
  explicit Client(ClientConfig cfg) : cfg_(std::move(cfg)) {
    std::lock_guard<std::mutex> lk(g_registry_mu);
    g_clients.push_back(this);
    for (auto& h : cfg_.hosts) g_hosts.insert(h);
    if (cfg_.consumer_type == 2)
      async_ = (g_mixed_counter++ % 2) == 1;
    else
      async_ = cfg_.consumer_type == 1;
  }

  bool connect() {
    auto conn = connect_with_retry(cfg_, cfg_.connect_retry_ms);
    if (!conn) return false;
    std::lock_guard<std::mutex> lk(mu_);
    conn_ = conn;
    initialized_ = false;
    return true;
  }

  // lazy channel/consumer init (Utils.java:319-325)
  bool initialize_if_necessary() {
    std::shared_ptr<Connection> c;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
      if (!c) return false;
      if (initialized_) return c->alive();
    }
    try {
      declare_queues_once(*c);
      c->enable_confirms();
      if (async_ && !c->start_consumer(QUEUE_NAME)) return false;
    } catch (const std::exception& e) {
      logf("initialize on %s failed: %s", cfg_.host.c_str(), e.what());
      return false;
    }
    std::lock_guard<std::mutex> lk(mu_);
    initialized_ = true;
    return true;
  }

  void declare_queues_once(Connection& c) {
    std::lock_guard<std::mutex> lk(g_registry_mu);
    if (g_queues_declared) return;
    // quorum queue args (Utils.java:327-374)
    amqp::Table args;
    args.put_str("x-queue-type", "quorum");
    if (cfg_.quorum_group_size > 0)
      args.put_int("x-quorum-initial-group-size", cfg_.quorum_group_size);
    if (cfg_.dead_letter) {
      args.put_str("x-dead-letter-exchange", "");
      args.put_str("x-dead-letter-routing-key", DLQ_NAME);
      args.put_str("x-dead-letter-strategy", "at-least-once");
      args.put_str("x-overflow", "reject-publish");
      args.put_int("x-message-ttl", MESSAGE_TTL_MS);
    }
    if (!c.declare_queue(QUEUE_NAME, args))
      throw std::runtime_error("queue.declare failed");
    if (cfg_.dead_letter) {
      amqp::Table dlq_args;
      dlq_args.put_str("x-queue-type", "quorum");
      if (!c.declare_queue(DLQ_NAME, dlq_args))
        throw std::runtime_error("dlq declare failed");
      if (!c.purge_queue(DLQ_NAME)) throw std::runtime_error("dlq purge");
    }
    if (!c.purge_queue(QUEUE_NAME)) throw std::runtime_error("purge failed");
    g_queues_declared = true;
  }

  // 1 ok, 0 nack, -1 timeout, -2 error
  int enqueue(int32_t value, int timeout_ms) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    return c->publish_confirm(QUEUE_NAME, value, timeout_ms);
  }

  // status: 1 = message (value in *out), 0 = empty, -1 = timeout,
  // -2 = connection error  (hard deadline, Utils.java:387-401)
  int dequeue(int timeout_ms, int32_t* out) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    if (async_) {
      Delivery d;
      int r = c->pop_delivery(&d, timeout_ms);
      if (r == 1) {
        c->basic_ack(d.tag);
        *out = d.value;
        return 1;
      }
      return r == -1 ? -1 : -2;  // deque timeout = op timeout
    }
    int32_t value;
    uint64_t tag;
    int r = c->basic_get(QUEUE_NAME, &value, &tag, timeout_ms);
    if (r == 1) {
      c->basic_ack(tag);
      *out = value;
      return 1;
    }
    if (r == 0) return 0;
    return r == -1 ? -1 : -2;
  }

  void close_connection() {
    std::shared_ptr<Connection> c;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
      conn_.reset();
      initialized_ = false;
    }
    if (c) c->close();
  }

  bool reconnect() {
    // async consumers clear their local deque so un-acked messages
    // requeue broker-side (Utils.java:543-555)
    close_connection();
    return connect();
  }

  const ClientConfig& config() const { return cfg_; }

 private:
  std::shared_ptr<Connection> conn() {
    std::lock_guard<std::mutex> lk(mu_);
    return conn_;
  }
  ClientConfig cfg_;
  std::mutex mu_;
  std::shared_ptr<Connection> conn_;
  bool initialized_ = false;
  bool async_ = false;
};

// ---------------------------------------------------------------------------
// Stream client (BASELINE config #4): append-only log over AMQP 0-9-1 —
// x-queue-type=stream declaration, confirmed appends, and non-destructive
// offset reads via basic.consume with the x-stream-offset argument; each
// delivery's log offset arrives in the x-stream-offset message header.
// ---------------------------------------------------------------------------

constexpr const char* STREAM_QUEUE_NAME = "jepsen.stream";
constexpr const char* STREAM_CONSUMER_TAG = "jt-stream-reader";
bool g_stream_declared = false;  // once-latch, like g_queues_declared

// Read up to max_n records of a stream queue from `offset`: attach a
// consumer at the offset, collect deliveries until max_n / overall
// deadline / a quiet window after the last delivery (the log end has no
// explicit marker over AMQP), then cancel.  Returns the count (≥0) or -2
// on error.  Shared by the stream client and the txn client's per-key
// reads.
long read_stream_queue(const std::shared_ptr<Connection>& c,
                       const std::string& queue, const std::string& ctag,
                       int64_t offset, long max_n, int timeout_ms,
                       int64_t* offsets_out, int32_t* values_out, long cap) {
  c->clear_deliveries();
  amqp::Table args;
  args.put_long("x-stream-offset", offset);
  int prefetch = static_cast<int>(std::min<long>(max_n, 1000));
  if (!c->start_consumer(queue, prefetch, &args, ctag)) return -2;
  long n = 0;
  int64_t next_implicit = offset;  // fallback when no offset header
  auto deadline = Clock::now() + milliseconds(timeout_ms);
  const int quiet_ms = 250;
  while (n < max_n && n < cap) {
    auto now = Clock::now();
    if (now >= deadline) break;
    int wait_ms = static_cast<int>(
        std::chrono::duration_cast<milliseconds>(deadline - now).count());
    if (n > 0) wait_ms = std::min(wait_ms, quiet_ms);
    Delivery d;
    int r = c->pop_delivery(&d, wait_ms);
    if (r == 1) {
      c->basic_ack(d.tag);
      int64_t off = d.offset >= 0 ? d.offset : next_implicit;
      next_implicit = off + 1;
      if (off >= offset) {  // broker may round down to a chunk boundary
        if (offsets_out) offsets_out[n] = off;
        values_out[n] = d.value;
        ++n;
      }
    } else if (r == -1) {
      break;  // deadline or quiet window elapsed
    } else {
      c->cancel_consumer(ctag);
      return n > 0 ? n : -2;
    }
  }
  c->cancel_consumer(ctag);
  c->clear_deliveries();
  return n;
}

class StreamClient {
 public:
  explicit StreamClient(ClientConfig cfg) : cfg_(std::move(cfg)) {}

  bool connect() {
    auto conn = connect_with_retry(cfg_, cfg_.connect_retry_ms);
    if (!conn) return false;
    std::lock_guard<std::mutex> lk(mu_);
    conn_ = conn;
    initialized_ = false;
    return true;
  }

  bool initialize_if_necessary() {
    std::shared_ptr<Connection> c;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
      if (!c) return false;
      if (initialized_) return c->alive();
    }
    try {
      {
        std::lock_guard<std::mutex> lk(g_registry_mu);
        if (!g_stream_declared) {
          amqp::Table args;
          args.put_str("x-queue-type", "stream");
          if (!c->declare_queue(STREAM_QUEUE_NAME, args))
            throw std::runtime_error("stream declare failed");
          // streams cannot be purged; a fresh run uses reset() + a fresh
          // broker (CI tears clusters down between runs)
          g_stream_declared = true;
        }
      }
      c->enable_confirms();
    } catch (const std::exception& e) {
      logf("stream initialize on %s failed: %s", cfg_.host.c_str(), e.what());
      return false;
    }
    std::lock_guard<std::mutex> lk(mu_);
    initialized_ = true;
    return true;
  }

  // 1 ok, 0 nack, -1 timeout, -2 error
  int append(int32_t value, int timeout_ms) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    return c->publish_confirm(STREAM_QUEUE_NAME, value, timeout_ms);
  }

  // See read_stream_queue above; returns the count (≥0) or -2 on error.
  long read_from(int64_t offset, long max_n, int timeout_ms,
                 int64_t* offsets_out, int32_t* values_out, long cap) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    return read_stream_queue(c, STREAM_QUEUE_NAME, STREAM_CONSUMER_TAG,
                             offset, max_n, timeout_ms, offsets_out,
                             values_out, cap);
  }

  // The log's last committed offset, probed with an
  // x-stream-offset="last" consumer (the string spec attaches at the
  // final chunk; the max delivered offset is the answer).  Returns the
  // offset (>=0), -1 when nothing was delivered within the timeout
  // (empty log OR a stalled broker — the caller must treat -1 as
  // unknown, never as proof of emptiness), -2 on error.
  //
  // Honesty note: AMQP 0-9-1 has no authoritative end-of-log marker, so
  // a broker that stalls >quiet_ms mid-final-chunk can still understate
  // the answer.  The proof this provides is therefore probabilistic but
  // strong: truncating the full read now needs *correlated* stalls at
  // the same boundary in the read AND both probes (the client probes
  // before and after), where the old empties heuristic needed a single
  // stall of ~2x the read timeout anywhere.  quiet_ms is double the
  // read path's: an understated probe is worse than a slow one.
  int64_t last_offset(int timeout_ms) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    c->clear_deliveries();
    amqp::Table args;
    args.put_str("x-stream-offset", "last");
    if (!c->start_consumer(STREAM_QUEUE_NAME, 100, &args, "jt-stream-last"))
      return -2;
    int64_t last = -1;
    auto deadline = Clock::now() + milliseconds(timeout_ms);
    const int quiet_ms = 500;
    for (;;) {
      auto now = Clock::now();
      if (now >= deadline) break;
      int wait_ms = static_cast<int>(
          std::chrono::duration_cast<milliseconds>(deadline - now).count());
      if (last >= 0) wait_ms = std::min(wait_ms, quiet_ms);
      Delivery d;
      int r = c->pop_delivery(&d, wait_ms);
      if (r == 1) {
        c->basic_ack(d.tag);
        if (d.offset > last) last = d.offset;
      } else if (r == -1) {
        break;  // deadline or quiet window elapsed
      } else {
        // connection error mid-probe: a partially-collected max is NOT
        // "the last committed offset" — presenting it would let the
        // client conclude end-of-log short of the truth
        c->cancel_consumer("jt-stream-last");
        return -2;
      }
    }
    c->cancel_consumer("jt-stream-last");
    c->clear_deliveries();
    return last;
  }

  void close_connection() {
    std::shared_ptr<Connection> c;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
      conn_.reset();
      initialized_ = false;
    }
    if (c) c->close();
  }

  bool reconnect() {
    close_connection();
    return connect();
  }

 private:
  std::shared_ptr<Connection> conn() {
    std::lock_guard<std::mutex> lk(mu_);
    return conn_;
  }
  ClientConfig cfg_;
  std::mutex mu_;
  std::shared_ptr<Connection> conn_;
  bool initialized_ = false;
};

// ---------------------------------------------------------------------------
// Transactional client (BASELINE config #5): Elle list-append over AMQP tx.
// Each key k lives in its own append-only stream queue ("elle.k<k>"); a
// txn's appends ride one AMQP transaction (tx.select once per channel,
// fire-and-forget basic.publish per append, then tx.commit — the commit-ok
// is the atomic visibility point), and reads re-read the key's whole
// stream non-destructively from offset 0.  tx wire constants:
// amqp_wire.hpp CLS_TX/M_TX_*.
// ---------------------------------------------------------------------------

class TxnClient {
 public:
  explicit TxnClient(ClientConfig cfg) : cfg_(std::move(cfg)) {}

  static std::string key_queue(int32_t key) {
    return "elle.k" + std::to_string(key);
  }

  bool connect() {
    auto conn = connect_with_retry(cfg_, cfg_.connect_retry_ms);
    if (!conn) return false;
    std::lock_guard<std::mutex> lk(mu_);
    conn_ = conn;
    rconn_.reset();  // lazily reopened by the next read
    initialized_ = false;
    declared_.clear();
    return true;
  }

  bool initialize_if_necessary() {
    std::shared_ptr<Connection> c;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
      if (!c) return false;
      if (initialized_) return c->alive();
    }
    if (!c->tx_select()) {
      logf("tx.select on %s failed", cfg_.host.c_str());
      return false;
    }
    std::lock_guard<std::mutex> lk(mu_);
    initialized_ = true;
    return true;
  }

  // 0 staged (visible at commit), -2 error
  int append(int32_t key, int32_t value) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c || !ensure_declared(c, key)) return -2;
    return c->publish_plain(key_queue(key), value) ? 0 : -2;
  }

  // 1 committed, -1 outcome unknown, -2 determinate error.  Anything but
  // success poisons the connection: AMQP tx replies carry no correlation
  // id, so a late commit-ok left in flight could otherwise be matched to
  // the NEXT txn's commit and report it committed prematurely.
  int commit(int timeout_ms) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    int r = c->tx_commit(timeout_ms);
    if (r != 1) close_connection();
    return r;
  }

  // 0 rolled back, -2 error
  int rollback(int timeout_ms) {
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    return c->tx_rollback(timeout_ms) ? 0 : -2;
  }

  // Committed list for the key, oldest first; count (≥0) or -2 on error.
  // Reads run on a dedicated NON-tx connection: on a real broker the
  // tx.select-ed channel buffers basic.acks until commit, so a stream
  // consumer there would stall at the prefetch window (credit never
  // replenishes) and silently truncate long reads — and a non-tx
  // connection also guarantees reads observe committed state only.
  long read_key(int32_t key, long max_n, int timeout_ms,
                int32_t* values_out, long cap) {
    auto c = read_conn();
    if (!c || !ensure_declared(c, key)) return -2;
    return read_stream_queue(c, key_queue(key), "jt-txn-reader", 0, max_n,
                             timeout_ms, nullptr, values_out, cap);
  }

  void close_connection() {
    std::shared_ptr<Connection> c, rc;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
      rc = rconn_;
      conn_.reset();
      rconn_.reset();
      initialized_ = false;
      declared_.clear();
    }
    if (c) c->close();
    if (rc) rc->close();
  }

  bool reconnect() {
    close_connection();
    return connect();
  }

 private:
  std::shared_ptr<Connection> conn() {
    std::lock_guard<std::mutex> lk(mu_);
    return conn_;
  }

  // lazily-opened plain (non-tx) connection for stream reads
  std::shared_ptr<Connection> read_conn() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (rconn_ && rconn_->alive()) return rconn_;
    }
    auto hp = split_host_port(cfg_.host, cfg_.port);
    auto rc = std::make_shared<Connection>(hp.first, hp.second, cfg_.user,
                                           cfg_.pass);
    if (!rc->open(5000)) return nullptr;
    std::lock_guard<std::mutex> lk(mu_);
    rconn_ = rc;
    return rconn_;
  }

  bool ensure_declared(const std::shared_ptr<Connection>& c, int32_t key) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (declared_.count(key)) return true;
    }
    amqp::Table args;
    args.put_str("x-queue-type", "stream");
    if (!c->declare_queue(key_queue(key), args)) return false;
    std::lock_guard<std::mutex> lk(mu_);
    declared_.insert(key);
    return true;
  }

  ClientConfig cfg_;
  std::mutex mu_;
  std::shared_ptr<Connection> conn_;
  std::shared_ptr<Connection> rconn_;
  bool initialized_ = false;
  std::set<int32_t> declared_;
};

// ---------------------------------------------------------------------------
// Lock client (the reference's legacy mutex variant, rabbitmq_test.clj:18-44,
// made live): a single-token lock over a quorum queue.  Setup publishes ONE
// token message into "jepsen.lock"; acquire = basic.get with manual ack,
// holding the delivery un-acked — the broker will not hand the token to any
// other connection while this one lives; release = basic.reject(requeue),
// returning the token.  A connection drop while holding REVOKES the lock
// broker-side (the token requeues) without the holder's consent — the
// classic unfenced-lock hazard.  The driver does not hide it: a holder that
// reconnects simply is not the holder any more, and any resulting double
// grant lands in the history for the linearizability checker to flag.
// ---------------------------------------------------------------------------

constexpr const char* LOCK_QUEUE_NAME = "jepsen.lock";
constexpr int32_t LOCK_TOKEN_VALUE = 1;
bool g_lock_declared = false;  // once-latch, like g_queues_declared

class LockClient {
 public:
  explicit LockClient(ClientConfig cfg) : cfg_(std::move(cfg)) {}

  bool connect(int budget_ms = 0) {
    auto conn = connect_with_retry(
        cfg_, budget_ms > 0 ? budget_ms : cfg_.connect_retry_ms);
    if (!conn) return false;
    std::lock_guard<std::mutex> lk(mu_);
    conn_ = conn;
    // a fresh connection cannot hold: any token the old one held
    // un-acked requeued broker-side when it died
    holding_ = false;
    poisoned_ = false;
    return true;
  }

  bool initialize_if_necessary() {
    std::shared_ptr<Connection> c;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
    }
    if (!c || !c->alive()) return false;
    std::lock_guard<std::mutex> lk(g_registry_mu);
    if (g_lock_declared) return true;
    try {
      amqp::Table args;
      args.put_str("x-queue-type", "quorum");
      if (cfg_.quorum_group_size > 0)
        args.put_int("x-quorum-initial-group-size", cfg_.quorum_group_size);
      if (cfg_.fenced) args.put_bool("x-fencing", true);
      if (!c->declare_queue(LOCK_QUEUE_NAME, args))
        throw std::runtime_error("lock queue.declare failed");
      if (!c->purge_queue(LOCK_QUEUE_NAME))
        throw std::runtime_error("lock purge failed");
      c->enable_confirms();
      if (c->publish_confirm(LOCK_QUEUE_NAME, LOCK_TOKEN_VALUE, 5000) != 1)
        throw std::runtime_error("lock token publish not confirmed");
    } catch (const std::exception& e) {
      logf("lock initialize on %s failed: %s", cfg_.host.c_str(), e.what());
      // tear the connection down: an UNCONFIRMED token publish may still
      // be in flight on it, and a retry (ours or another client's) would
      // purge-then-republish, leaving TWO tokens once the stray lands —
      // a harness-made double grant.  Closing narrows that window to
      // frames already accepted by the broker's socket.
      close_connection();
      return false;
    }
    g_lock_declared = true;
    return true;
  }

  // 1 granted, 0 busy (or we already hold), -1 outcome unknown, -2 error.
  // In fenced mode a grant also fills *token_out with the fencing token
  // the broker attached (the Raft log index of the grant commit).
  int acquire(int timeout_ms, int64_t* token_out = nullptr) {
    if (!clear_poison(timeout_ms)) return -2;
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (holding_) return 0;  // we hold the token: the queue is empty
    }
    int32_t v = 0;
    uint64_t tag = 0;
    int64_t fence = -1;
    int r = c->basic_get(LOCK_QUEUE_NAME, &v, &tag, timeout_ms, &fence);
    if (r == 1) {
      if (cfg_.fenced && fence <= 0) {
        // a fenced client granted a token WITHOUT a fencing header means
        // the queue was not fenced-declared (mixed-mode misconfig):
        // surface loudly rather than fabricate a token.  The grant is
        // returned via reject so the lock is not silently parked.
        c->basic_reject_requeue(tag);
        logf("fenced acquire got no x-fence-token from %s",
             cfg_.host.c_str());
        return -2;
      }
      std::lock_guard<std::mutex> lk(mu_);
      holding_ = true;
      tag_ = tag;
      token_ = fence;
      if (token_out) *token_out = fence;
      return 1;
    }
    if (r == 0) return 0;
    if (r == -1) {
      // the get reached the wire but no answer came: the broker may be
      // handing us the token right now.  Poison the connection — the next
      // op tears it down (requeueing any in-flight grant) — so an
      // indeterminate acquire cannot park the token un-acked forever.
      std::lock_guard<std::mutex> lk(mu_);
      poisoned_ = true;
      return -1;
    }
    return -2;
  }

  // 1 released, 0 not the holder, -1 outcome unknown, -2 error.
  // Fenced mode fills *token_out with the token the release used.
  int release(int timeout_ms, int64_t* token_out = nullptr) {
    if (cfg_.fenced) return release_fenced(timeout_ms, token_out);
    // reject carries no *-ok: outcome is known at send; timeout_ms only
    // bounds the poisoned-path reconnect below
    bool poisoned, holding;
    {
      std::lock_guard<std::mutex> lk(mu_);
      poisoned = poisoned_;
      holding = holding_;
    }
    if (poisoned) {
      // an earlier acquire's outcome is unknown; reconnecting requeues any
      // token that get left un-acked, but whether WE were the holder is
      // unknowable — so is this release's outcome.  The reconnect is
      // bounded by the op's own timeout, never the 30 s connect budget.
      close_connection();
      connect(timeout_ms > 0 ? timeout_ms : 1000);
      return -1;
    }
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    if (!holding) return 0;
    uint64_t tag;
    {
      std::lock_guard<std::mutex> lk(mu_);
      tag = tag_;
    }
    if (c->basic_reject_requeue(tag)) {
      // reject carries no *-ok, so without a barrier a contender's
      // immediately-following basic.get can race the requeue and see an
      // empty queue.  A cheap RPC behind it (idempotent re-declare) rides
      // the channel's in-order processing: once it answers, the reject
      // was processed and the token is back.  If the barrier fails the
      // connection broke after the reject was sent — the token returns
      // either way (processed reject, or requeue when the broker reaps
      // the connection), so the release still happened.
      amqp::Table args;
      args.put_str("x-queue-type", "quorum");
      if (cfg_.quorum_group_size > 0)
        args.put_int("x-quorum-initial-group-size", cfg_.quorum_group_size);
      c->declare_queue(LOCK_QUEUE_NAME, args);
      std::lock_guard<std::mutex> lk(mu_);
      holding_ = false;
      return 1;
    }
    // the reject never left this process and the connection is now broken:
    // the broker requeues the token when it reaps the connection — the
    // release happens, at an unknown point
    {
      std::lock_guard<std::mutex> lk(mu_);
      holding_ = false;
    }
    return -1;
  }

  // Fenced release: publish the token back bearing `x-fence-release:
  // <token>`.  The broker accepts (confirm) iff the token is still the
  // queue's current fence, atomically settling our grant and returning
  // the token; a nack means the grant was revoked and re-granted since —
  // we are NOT the holder, and no stale-token operation succeeded.
  int release_fenced(int timeout_ms, int64_t* token_out) {
    bool poisoned, holding;
    int64_t tok;
    {
      std::lock_guard<std::mutex> lk(mu_);
      poisoned = poisoned_;
      holding = holding_;
      tok = token_;
    }
    if (poisoned) {
      // an earlier acquire's outcome is unknown: whether we hold (and
      // with which token) is unknowable — reconnect requeues any parked
      // grant, and this release is indeterminate
      close_connection();
      connect(timeout_ms > 0 ? timeout_ms : 1000);
      return -1;
    }
    if (!initialize_if_necessary()) return -2;
    auto c = conn();
    if (!c) return -2;
    if (!holding) return 0;
    if (!c->ensure_confirms()) return -2;
    amqp::Writer entries;
    entries.shortstr("x-fence-release");
    entries.u8('l');
    entries.u64(static_cast<uint64_t>(tok));
    amqp::Writer props;
    props.u16(0x2000);  // headers present
    props.u32(static_cast<uint32_t>(entries.buf.size()));
    props.bytes(entries.buf.data(), entries.buf.size());
    int r = c->publish_confirm_props(
        LOCK_QUEUE_NAME, std::to_string(LOCK_TOKEN_VALUE), &props.buf,
        timeout_ms);
    if (token_out) *token_out = tok;
    if (r == 1) {
      std::lock_guard<std::mutex> lk(mu_);
      holding_ = false;
      return 1;
    }
    if (r == 0) {
      // stale: the broker REJECTED the release — our grant was revoked
      // (and possibly re-granted) behind our back.  We are not the
      // holder; the un-acked delivery our connection still parks is a
      // settled ghost the broker has already scrubbed or will requeue
      // harmlessly (a revoked token message carries no fence).
      std::lock_guard<std::mutex> lk(mu_);
      holding_ = false;
      return 0;
    }
    if (r == -1) {
      // the publish reached the wire but no confirm came: the release
      // may or may not have committed — poison, like an indeterminate
      // acquire, so the next op tears the connection down
      std::lock_guard<std::mutex> lk(mu_);
      poisoned_ = true;
      return -1;
    }
    return -2;
  }

  void close_connection() {
    std::shared_ptr<Connection> c;
    {
      std::lock_guard<std::mutex> lk(mu_);
      c = conn_;
      conn_.reset();
      holding_ = false;
      poisoned_ = false;
    }
    if (c) c->close();
  }

  bool reconnect() {
    close_connection();
    return connect();
  }

 private:
  std::shared_ptr<Connection> conn() {
    std::lock_guard<std::mutex> lk(mu_);
    return conn_;
  }

  // a poisoned connection (indeterminate basic.get in flight) must be
  // torn down before the next op; the replacement connect is bounded by
  // the op's timeout so a partition can't stall a 5 s op for the full
  // 30 s connect budget (reconnection policy stays with the test layer)
  bool clear_poison(int timeout_ms) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!poisoned_) return true;
    }
    close_connection();
    return connect(timeout_ms > 0 ? timeout_ms : 1000);
  }

  ClientConfig cfg_;
  std::mutex mu_;
  std::shared_ptr<Connection> conn_;
  bool holding_ = false;
  bool poisoned_ = false;
  uint64_t tag_ = 0;
  int64_t token_ = -1;  // fenced mode: the held grant's fencing token
};

// drain: the correctness-critical final read (Utils.java:413-470)
long drain_impl(Client* self, int32_t* out, long cap) {
  {
    std::unique_lock<std::mutex> lk(g_registry_mu);
    if (g_drained) {
      // someone already drained: wait for completion, return empty
      g_drain_cv.wait(lk, [] { return g_drain_done; });
      return 0;
    }
    g_drained = true;
  }
  // close ALL clients so un-acked deliveries requeue
  std::vector<Client*> clients;
  std::set<std::string> hosts;
  bool dead_letter = false;
  {
    std::lock_guard<std::mutex> lk(g_registry_mu);
    clients = g_clients;
    hosts = g_hosts;
    dead_letter = self->config().dead_letter;
  }
  for (auto* c : clients) c->close_connection();
  std::this_thread::sleep_for(milliseconds(g_drain_wait_ms));

  // Multi-pass: the close() above makes the broker requeue every
  // un-acked delivery, but those requeues land asynchronously (on a
  // replicated broker they are quorum commits) — a single pass that
  // happens to observe get-empty before a late requeue would leave
  // committed messages behind and read as loss.  Repeat until a CLEAN
  // full pass over every host drains nothing new (settle sleep between
  // passes), bounded so a live publisher can't spin us forever.
  //
  // CLEAN matters (the r7 soak's acked-loss signature: a large block of
  // confirmed values "lost" while actually still READY cluster-wide):
  // basic_get answers 0 only on an authoritative get-empty from the
  // broker; -1 is a TIMEOUT (e.g. the cluster mid-election cannot
  // commit the DEQ) and -2 a broken connection.  The old quiet-pass
  // exit counted those exactly like get-empty, so a pass that never
  // reached quorum on any node — trivially "drained nothing new" —
  // ended the drain with committed messages still queued, and the
  // checker read them as lost.  A pass now only ends the drain when it
  // is quiet AND every host answered authoritatively.
  std::vector<int32_t> values;
  for (int pass = 0; pass < 8; ++pass) {
    if (pass > 0)
      std::this_thread::sleep_for(milliseconds(g_drain_wait_ms));
    size_t before = values.size();
    bool dirty = false;  // any unreachable host / timed-out / broken get
    for (const auto& host : hosts) {
      auto hp = split_host_port(host, self->config().port);
      Connection conn(hp.first, hp.second, self->config().user,
                      self->config().pass);
      if (!conn.open(5000)) {
        logf("drain: cannot connect to %s", host.c_str());
        dirty = true;
        continue;
      }
      std::vector<std::string> queues = {QUEUE_NAME};
      if (dead_letter) queues.push_back(DLQ_NAME);
      for (const auto& q : queues) {
        while (true) {
          int32_t value;
          uint64_t tag;
          int r = conn.basic_get(q, &value, &tag, 5000);
          if (r == 1) {
            conn.basic_ack(tag);
            values.push_back(value);
            continue;
          }
          if (r != 0) {
            logf("drain: get on %s gave %d (not an authoritative "
                 "empty) — pass stays dirty", host.c_str(), r);
            dirty = true;
          }
          break;
        }
      }
      conn.close();
    }
    if (pass > 0 && values.size() == before && !dirty) break;
  }
  {
    std::lock_guard<std::mutex> lk(g_registry_mu);
    g_drain_result = values;
    g_drain_done = true;
  }
  g_drain_cv.notify_all();
  long n = std::min<long>(values.size(), cap);
  for (long i = 0; i < n; ++i) out[i] = values[i];
  return n;
}

}  // namespace

extern "C" {

void* amqp_client_create(const char* hosts_csv, const char* host, int port,
                         const char* user, const char* pass,
                         int consumer_type, int quorum_group_size,
                         int dead_letter, int connect_retry_ms) {
  ClientConfig cfg;
  std::string csv(hosts_csv ? hosts_csv : "");
  size_t start = 0;
  while (start <= csv.size() && !csv.empty()) {
    size_t comma = csv.find(',', start);
    std::string h = csv.substr(
        start, comma == std::string::npos ? std::string::npos : comma - start);
    if (!h.empty()) cfg.hosts.push_back(h);
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  cfg.host = host ? host : "localhost";
  cfg.port = port;
  if (user) cfg.user = user;
  if (pass) cfg.pass = pass;
  cfg.consumer_type = consumer_type;
  cfg.quorum_group_size = quorum_group_size;
  cfg.dead_letter = dead_letter != 0;
  if (connect_retry_ms > 0) cfg.connect_retry_ms = connect_retry_ms;
  auto* c = new Client(std::move(cfg));
  if (!c->connect()) {
    // keep the object (caller may reconnect); report via setup/enqueue codes
    logf("initial connect failed for %s", c->config().host.c_str());
  }
  return c;
}

int amqp_client_setup(void* p) {
  auto* c = static_cast<Client*>(p);
  return c->initialize_if_necessary() ? 0 : -1;
}

int amqp_client_enqueue(void* p, int value, int timeout_ms) {
  return static_cast<Client*>(p)->enqueue(value, timeout_ms);
}

int amqp_client_dequeue(void* p, int timeout_ms, int* value_out) {
  int32_t v = 0;
  int status = static_cast<Client*>(p)->dequeue(timeout_ms, &v);
  if (status == 1 && value_out) *value_out = v;
  return status;
}

long amqp_client_drain(void* p, int* out, long cap) {
  return drain_impl(static_cast<Client*>(p), out, cap);
}

int amqp_client_reconnect(void* p) {
  return static_cast<Client*>(p)->reconnect() ? 0 : -1;
}

void amqp_client_close(void* p) {
  static_cast<Client*>(p)->close_connection();
}

void amqp_client_destroy(void* p) {
  auto* c = static_cast<Client*>(p);
  c->close_connection();
  std::lock_guard<std::mutex> lk(g_registry_mu);
  g_clients.erase(std::remove(g_clients.begin(), g_clients.end(), c),
                  g_clients.end());
  delete c;
}

// ---- stream client ABI ----------------------------------------------------

void* amqp_stream_client_create(const char* host, int port, const char* user,
                                const char* pass, int connect_retry_ms) {
  ClientConfig cfg;
  cfg.host = host ? host : "localhost";
  cfg.port = port;
  if (user) cfg.user = user;
  if (pass) cfg.pass = pass;
  if (connect_retry_ms > 0) cfg.connect_retry_ms = connect_retry_ms;
  auto* c = new StreamClient(std::move(cfg));
  if (!c->connect())
    logf("initial stream connect failed for %s", host ? host : "?");
  return c;
}

int amqp_stream_client_setup(void* p) {
  return static_cast<StreamClient*>(p)->initialize_if_necessary() ? 0 : -1;
}

int amqp_stream_append(void* p, int value, int timeout_ms) {
  return static_cast<StreamClient*>(p)->append(value, timeout_ms);
}

long amqp_stream_read_from(void* p, long long offset, long max_n,
                           int timeout_ms, long long* offsets_out,
                           int* values_out, long cap) {
  return static_cast<StreamClient*>(p)->read_from(
      offset, max_n, timeout_ms,
      reinterpret_cast<int64_t*>(offsets_out), values_out, cap);
}

long long amqp_stream_last_offset(void* p, int timeout_ms) {
  return static_cast<StreamClient*>(p)->last_offset(timeout_ms);
}

int amqp_stream_reconnect(void* p) {
  return static_cast<StreamClient*>(p)->reconnect() ? 0 : -1;
}

void amqp_stream_close(void* p) {
  static_cast<StreamClient*>(p)->close_connection();
}

void amqp_stream_destroy(void* p) {
  auto* c = static_cast<StreamClient*>(p);
  c->close_connection();
  delete c;
}

// ---- txn client ABI (Elle list-append over AMQP tx) -----------------------

void* amqp_txn_client_create(const char* host, int port, const char* user,
                             const char* pass, int connect_retry_ms) {
  ClientConfig cfg;
  cfg.host = host ? host : "localhost";
  cfg.port = port;
  if (user) cfg.user = user;
  if (pass) cfg.pass = pass;
  if (connect_retry_ms > 0) cfg.connect_retry_ms = connect_retry_ms;
  auto* c = new TxnClient(std::move(cfg));
  if (!c->connect())
    logf("initial txn connect failed for %s", host ? host : "?");
  return c;
}

int amqp_txn_client_setup(void* p) {
  return static_cast<TxnClient*>(p)->initialize_if_necessary() ? 0 : -1;
}

int amqp_txn_append(void* p, int key, int value) {
  return static_cast<TxnClient*>(p)->append(key, value);
}

int amqp_txn_commit(void* p, int timeout_ms) {
  return static_cast<TxnClient*>(p)->commit(timeout_ms);
}

int amqp_txn_rollback(void* p, int timeout_ms) {
  return static_cast<TxnClient*>(p)->rollback(timeout_ms);
}

long amqp_txn_read_key(void* p, int key, int timeout_ms, int* values_out,
                       long cap) {
  return static_cast<TxnClient*>(p)->read_key(key, cap, timeout_ms,
                                              values_out, cap);
}

int amqp_txn_reconnect(void* p) {
  return static_cast<TxnClient*>(p)->reconnect() ? 0 : -1;
}

void amqp_txn_close(void* p) {
  static_cast<TxnClient*>(p)->close_connection();
}

void amqp_txn_destroy(void* p) {
  auto* c = static_cast<TxnClient*>(p);
  c->close_connection();
  delete c;
}

// ---- lock client ABI (legacy mutex variant, live) -------------------------

void* amqp_lock_client_create(const char* host, int port, const char* user,
                              const char* pass, int quorum_group_size,
                              int connect_retry_ms, int fenced) {
  ClientConfig cfg;
  cfg.host = host ? host : "localhost";
  cfg.port = port;
  if (user) cfg.user = user;
  if (pass) cfg.pass = pass;
  cfg.quorum_group_size = quorum_group_size;
  if (connect_retry_ms > 0) cfg.connect_retry_ms = connect_retry_ms;
  cfg.fenced = fenced != 0;
  auto* c = new LockClient(std::move(cfg));
  if (!c->connect())
    logf("initial lock connect failed for %s", host ? host : "?");
  return c;
}

int amqp_lock_client_setup(void* p) {
  return static_cast<LockClient*>(p)->initialize_if_necessary() ? 0 : -1;
}

int amqp_lock_acquire(void* p, int timeout_ms) {
  return static_cast<LockClient*>(p)->acquire(timeout_ms);
}

int amqp_lock_release(void* p, int timeout_ms) {
  return static_cast<LockClient*>(p)->release(timeout_ms);
}

// fenced variants: *token_out carries the fencing token on a grant /
// the token a successful release used
int amqp_lock_acquire_fenced(void* p, int timeout_ms,
                             long long* token_out) {
  int64_t tok = -1;
  int r = static_cast<LockClient*>(p)->acquire(timeout_ms, &tok);
  if (token_out) *token_out = tok;
  return r;
}

int amqp_lock_release_fenced(void* p, int timeout_ms,
                             long long* token_out) {
  int64_t tok = -1;
  int r = static_cast<LockClient*>(p)->release(timeout_ms, &tok);
  if (token_out) *token_out = tok;
  return r;
}

int amqp_lock_reconnect(void* p) {
  return static_cast<LockClient*>(p)->reconnect() ? 0 : -1;
}

void amqp_lock_close(void* p) {
  static_cast<LockClient*>(p)->close_connection();
}

void amqp_lock_destroy(void* p) {
  auto* c = static_cast<LockClient*>(p);
  c->close_connection();
  delete c;
}

// test support (= Utils.reset(), Utils.java:147-152)
void amqp_reset(int drain_wait_ms) {
  std::lock_guard<std::mutex> lk(g_registry_mu);
  g_clients.clear();
  g_hosts.clear();
  g_queues_declared = false;
  g_stream_declared = false;
  g_lock_declared = false;
  g_drained = false;
  g_drain_done = false;
  g_drain_result.clear();
  g_mixed_counter = 0;
  if (drain_wait_ms >= 0) g_drain_wait_ms = drain_wait_ms;
}

void amqp_set_logging(int enabled) { g_log_enabled = enabled; }

// ---------------------------------------------------------------------------
// Codec-fuzz surface (round-3 verdict item #4).  The reference leans on a
// battle-tested client library (com.rabbitmq:amqp-client 5.34.0,
// project.clj:12); this from-scratch codec earns the same trust by
// differential fuzzing: random field tables (every type in RabbitMQ's
// field grammar, nested tables/arrays, boundary-length long strings) are
// encoded by one implementation, carried verbatim through the mini
// broker (optionally with fragmented TCP writes), and decoded by
// another — with rabbitmq-c (native/interop_probe.c fuzzpub/fuzzget) as
// the independent oracle on either end.  The planted x-stream-offset in
// each table is the checked invariant: finding it requires correctly
// skipping every random field before it.
// ---------------------------------------------------------------------------

static uint64_t fuzz_next(uint64_t* s) {  // splitmix64
  uint64_t z = (*s += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

static std::string fuzz_string(uint64_t* s, size_t max_len) {
  size_t n = fuzz_next(s) % (max_len + 1);
  std::string out;
  out.reserve(n);
  for (size_t i = 0; i < n; ++i)
    out.push_back(static_cast<char>(fuzz_next(s) & 0xFF));
  return out;
}

static std::string fuzz_key(uint64_t* s) {
  size_t n = 1 + fuzz_next(s) % 20;
  std::string out;
  for (size_t i = 0; i < n; ++i)
    out.push_back('a' + static_cast<char>(fuzz_next(s) % 26));
  return out;
}

// append one random field value (full RabbitMQ field grammar) to w
static void fuzz_field_value(amqp::Writer* w, uint64_t* s, int depth) {
  static const char kinds[] = "tbBsuIifldDSTVxFA";
  char k = kinds[fuzz_next(s) % (depth > 0 ? 17 : 15)];  // F/A only nested
  w->u8(static_cast<uint8_t>(k));
  switch (k) {
    case 't': case 'b': case 'B': w->u8(fuzz_next(s) & 0xFF); break;
    case 's': case 'u': w->u16(fuzz_next(s) & 0xFFFF); break;
    case 'I': case 'i': case 'f': w->u32(fuzz_next(s) & 0xFFFFFFFF); break;
    case 'l': case 'd': case 'T': w->u64(fuzz_next(s)); break;
    case 'D': w->u8(fuzz_next(s) & 0xFF); w->u32(fuzz_next(s)); break;
    case 'S': case 'x': {
      // mostly short, occasionally boundary-size long strings
      size_t cap = (fuzz_next(s) % 8 == 0) ? 8192 : 64;
      w->longstr(fuzz_string(s, cap));
      break;
    }
    case 'V': break;
    case 'F': {
      amqp::Writer entries;
      int n = fuzz_next(s) % 4;
      for (int i = 0; i < n; ++i) {
        entries.shortstr(fuzz_key(s));
        fuzz_field_value(&entries, s, depth - 1);
      }
      w->u32(static_cast<uint32_t>(entries.buf.size()));
      w->bytes(entries.buf.data(), entries.buf.size());
      break;
    }
    case 'A': {
      amqp::Writer items;
      int n = fuzz_next(s) % 4;
      for (int i = 0; i < n; ++i) fuzz_field_value(&items, s, depth - 1);
      w->u32(static_cast<uint32_t>(items.buf.size()));
      w->bytes(items.buf.data(), items.buf.size());
      break;
    }
  }
}

// properties bytes (flags + headers table): random junk fields with
// x-stream-offset = planted inserted at a random position
static std::vector<uint8_t> fuzz_props(uint64_t seed, int64_t planted) {
  uint64_t s = seed;
  amqp::Writer entries;
  int n_fields = fuzz_next(&s) % 8;
  int plant_at = static_cast<int>(fuzz_next(&s) % (n_fields + 1));
  for (int i = 0; i <= n_fields; ++i) {
    if (i == plant_at) {
      entries.shortstr("x-stream-offset");
      entries.u8('l');
      entries.u64(static_cast<uint64_t>(planted));
    } else {
      entries.shortstr(fuzz_key(&s));
      fuzz_field_value(&entries, &s, 2);
    }
  }
  amqp::Writer props;
  props.u16(0x2000);  // headers present
  props.u32(static_cast<uint32_t>(entries.buf.size()));
  props.bytes(entries.buf.data(), entries.buf.size());
  return props.buf;
}

// Publish n messages with fuzzed header tables (planted offset = base+i,
// body = i).  Returns the count published+confirmed, or -(i+1) on the
// first failure.
long long amqp_fuzz_publish_tables(const char* host, int port,
                                   const char* queue, long long seed,
                                   long long base, int n) {
  Connection conn(host ? host : "127.0.0.1", port, "guest", "guest");
  if (!conn.open(5000)) return -1000000;
  amqp::Table args;
  if (!conn.declare_queue(queue, args)) return -1000001;
  conn.enable_confirms();
  for (int i = 0; i < n; ++i) {
    auto props = fuzz_props(static_cast<uint64_t>(seed) + i, base + i);
    if (conn.publish_confirm_props(queue, std::to_string(i), &props,
                                   5000) != 1) {
      conn.close();
      return -(i + 1);
    }
  }
  conn.close();
  return n;
}

// Consume n messages; decode each header table with OUR reader
// (header_stream_offset must skip every fuzzed field to find the planted
// key) and parse the int body.  Fills offs/bodies; returns the count.
long amqp_fuzz_consume_offsets(const char* host, int port, const char* queue,
                               long n, long long* offs, int* bodies,
                               int timeout_ms) {
  Connection conn(host ? host : "127.0.0.1", port, "guest", "guest");
  if (!conn.open(5000)) return -1;
  if (!conn.start_consumer(queue, 200, nullptr, "fuzz-consumer")) {
    conn.close();
    return -2;
  }
  long got = 0;
  while (got < n) {
    Delivery d;
    int r = conn.pop_delivery(&d, timeout_ms);
    if (r != 1) break;
    conn.basic_ack(d.tag);
    offs[got] = d.offset;
    bodies[got] = d.value;
    ++got;
  }
  conn.close();
  return got;
}

}  // extern "C"
