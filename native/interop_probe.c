/* Independent-implementation interop probe.
 *
 * The reference validates its driver against a real broker on localhost
 * (reference UtilsTest.java:50).  This image has no installable broker
 * (zero egress; see native/BROKER_NOTE.md), so conformance is established
 * differentially instead: this program drives the framework's mini broker
 * (jepsen_tpu/harness/broker.py) through librabbitmq (rabbitmq-c, the
 * system's independently-authored AMQP 0-9-1 client), exercising the same
 * wire surface the C++ driver uses — handshake, queue.declare,
 * confirm.select, basic.publish + publisher confirm, basic.get,
 * basic.consume/deliver, tx.select/commit/rollback, and the stream
 * subset (x-queue-type=stream declare args, x-stream-offset consume arg,
 * per-delivery offset headers — the custom table grammar both in-tree
 * implementations must agree on with a third party).  A shared misreading
 * of the AMQP spec between the in-tree C++ codec (amqp_wire.hpp) and the
 * in-tree mini broker cannot survive this probe: rabbitmq-c would refuse
 * the frames.
 *
 * Only the public, soname-stable rabbitmq-c ABI is declared below (the
 * image ships librabbitmq.so.4 without headers).
 *
 * Usage: interop_probe HOST PORT [tx]   — exits 0 iff every step passed.
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>

/* ---- rabbitmq-c public ABI (librabbitmq.so.4) -------------------------- */

typedef int amqp_boolean_t;
typedef uint16_t amqp_channel_t;
typedef uint32_t amqp_flags_t;
typedef uint32_t amqp_method_number_t;

typedef struct {
  size_t len;
  void *bytes;
} amqp_bytes_t;

typedef struct amqp_connection_state_t_ *amqp_connection_state_t;
typedef struct amqp_socket_t_ amqp_socket_t;

typedef struct {
  amqp_method_number_t id;
  void *decoded;
} amqp_method_t;

typedef enum {
  AMQP_RESPONSE_NONE = 0,
  AMQP_RESPONSE_NORMAL,
  AMQP_RESPONSE_LIBRARY_EXCEPTION,
  AMQP_RESPONSE_SERVER_EXCEPTION
} amqp_response_type_enum;

typedef struct {
  amqp_response_type_enum reply_type;
  amqp_method_t reply;
  int library_error;
} amqp_rpc_reply_t;

typedef struct {
  int num_entries;
  void *entries; /* amqp_table_entry_t[], declared below */
} amqp_table_t;

typedef struct {
  uint8_t decimals;
  uint32_t value;
} amqp_decimal_t;

struct amqp_field_value_t_;

typedef struct {
  int num_entries;
  struct amqp_field_value_t_ *entries;
} amqp_array_t;

typedef struct amqp_field_value_t_ {
  uint8_t kind; /* 'S' utf8 longstr, 'l' int64, ... (rabbitmq-c amqp.h) */
  union {
    amqp_boolean_t boolean;
    int8_t i8;
    uint8_t u8;
    int16_t i16;
    uint16_t u16;
    int32_t i32;
    uint32_t u32;
    int64_t i64;
    uint64_t u64;
    float f32;
    double f64;
    amqp_decimal_t decimal;
    amqp_bytes_t bytes;
    amqp_table_t table;
    amqp_array_t array;
  } value;
} amqp_field_value_t;

typedef struct {
  amqp_bytes_t key;
  amqp_field_value_t value;
} amqp_table_entry_t;

typedef struct {
  int num_blocks;
  void **blocklist;
} amqp_pool_blocklist_t;

typedef struct {
  size_t pagesize;
  amqp_pool_blocklist_t pages;
  amqp_pool_blocklist_t large_blocks;
  int next_page;
  char *alloc_block;
  size_t alloc_used;
} amqp_pool_t;

typedef struct {
  amqp_flags_t _flags;
  amqp_bytes_t content_type;
  amqp_bytes_t content_encoding;
  amqp_table_t headers;
  uint8_t delivery_mode;
  uint8_t priority;
  amqp_bytes_t correlation_id;
  amqp_bytes_t reply_to;
  amqp_bytes_t expiration;
  amqp_bytes_t message_id;
  uint64_t timestamp;
  amqp_bytes_t type;
  amqp_bytes_t user_id;
  amqp_bytes_t app_id;
  amqp_bytes_t cluster_id;
} amqp_basic_properties_t;

typedef struct {
  amqp_basic_properties_t properties;
  amqp_bytes_t body;
  amqp_pool_t pool;
} amqp_message_t;

typedef struct {
  amqp_channel_t channel;
  amqp_bytes_t consumer_tag;
  uint64_t delivery_tag;
  amqp_boolean_t redelivered;
  amqp_bytes_t exchange;
  amqp_bytes_t routing_key;
  amqp_message_t message;
} amqp_envelope_t;

enum { AMQP_SASL_METHOD_PLAIN = 0 };

#define AMQP_BASIC_HEADERS_FLAG (1 << 13)

#define AMQP_BASIC_ACK_METHOD ((amqp_method_number_t)0x003C0050)
#define AMQP_BASIC_GET_OK_METHOD ((amqp_method_number_t)0x003C0047)
#define AMQP_BASIC_GET_EMPTY_METHOD ((amqp_method_number_t)0x003C0048)

extern const amqp_table_t amqp_empty_table;
extern const amqp_bytes_t amqp_empty_bytes;

amqp_connection_state_t amqp_new_connection(void);
int amqp_destroy_connection(amqp_connection_state_t);
amqp_socket_t *amqp_tcp_socket_new(amqp_connection_state_t);
int amqp_socket_open(amqp_socket_t *, const char *host, int port);
amqp_rpc_reply_t amqp_login(amqp_connection_state_t, const char *vhost,
                            int channel_max, int frame_max, int heartbeat,
                            int sasl_method, ...);
void *amqp_channel_open(amqp_connection_state_t, amqp_channel_t);
amqp_rpc_reply_t amqp_get_rpc_reply(amqp_connection_state_t);
void *amqp_queue_declare(amqp_connection_state_t, amqp_channel_t,
                         amqp_bytes_t queue, amqp_boolean_t passive,
                         amqp_boolean_t durable, amqp_boolean_t exclusive,
                         amqp_boolean_t auto_delete, amqp_table_t args);
void *amqp_confirm_select(amqp_connection_state_t, amqp_channel_t);
int amqp_basic_publish(amqp_connection_state_t, amqp_channel_t,
                       amqp_bytes_t exchange, amqp_bytes_t routing_key,
                       amqp_boolean_t mandatory, amqp_boolean_t immediate,
                       const amqp_basic_properties_t *, amqp_bytes_t body);
int amqp_simple_wait_method(amqp_connection_state_t, amqp_channel_t,
                            amqp_method_number_t expected,
                            amqp_method_t *output);
amqp_rpc_reply_t amqp_basic_get(amqp_connection_state_t, amqp_channel_t,
                                amqp_bytes_t queue, amqp_boolean_t no_ack);
amqp_rpc_reply_t amqp_read_message(amqp_connection_state_t, amqp_channel_t,
                                   amqp_message_t *, int flags);
void amqp_destroy_message(amqp_message_t *);
void *amqp_basic_consume(amqp_connection_state_t, amqp_channel_t,
                         amqp_bytes_t queue, amqp_bytes_t consumer_tag,
                         amqp_boolean_t no_local, amqp_boolean_t no_ack,
                         amqp_boolean_t exclusive, amqp_table_t args);
amqp_rpc_reply_t amqp_consume_message(amqp_connection_state_t,
                                      amqp_envelope_t *,
                                      const struct timeval *timeout,
                                      int flags);
void amqp_destroy_envelope(amqp_envelope_t *);
void *amqp_tx_select(amqp_connection_state_t, amqp_channel_t);
void *amqp_tx_commit(amqp_connection_state_t, amqp_channel_t);
void *amqp_tx_rollback(amqp_connection_state_t, amqp_channel_t);
amqp_bytes_t amqp_cstring_bytes(const char *);
void amqp_maybe_release_buffers(amqp_connection_state_t);

/* ---- probe ------------------------------------------------------------- */

#define CHECK(cond, what)                                   \
  do {                                                      \
    if (!(cond)) {                                          \
      fprintf(stderr, "PROBE FAIL: %s\n", what);            \
      return 1;                                             \
    }                                                       \
  } while (0)

#define CHECK_RPC(r, what)                                               \
  do {                                                                   \
    if ((r).reply_type != AMQP_RESPONSE_NORMAL) {                        \
      fprintf(stderr, "PROBE FAIL: %s (reply_type=%d lib_err=%d)\n",     \
              what, (int)(r).reply_type, (r).library_error);             \
      return 1;                                                          \
    }                                                                    \
  } while (0)

enum { N_MSGS = 16 };

static int body_int(amqp_bytes_t body) {
  char buf[32];
  size_t n = body.len < sizeof buf - 1 ? body.len : sizeof buf - 1;
  memcpy(buf, body.bytes, n);
  buf[n] = '\0';
  return atoi(buf);
}

static int publish_one_ch(amqp_connection_state_t c, amqp_channel_t ch,
                          const char *queue, int v, int want_confirm) {
  char buf[16];
  snprintf(buf, sizeof buf, "%d", v);
  int rc = amqp_basic_publish(c, ch, amqp_cstring_bytes(""),
                              amqp_cstring_bytes(queue), 1, 0, NULL,
                              amqp_cstring_bytes(buf));
  if (rc != 0) return -1;
  if (want_confirm) {
    amqp_method_t m;
    if (amqp_simple_wait_method(c, ch, AMQP_BASIC_ACK_METHOD, &m) != 0)
      return -2;
  }
  return 0;
}

static int publish_one(amqp_connection_state_t c, const char *queue, int v,
                       int want_confirm) {
  return publish_one_ch(c, 1, queue, v, want_confirm);
}

/* ---- codec fuzz (rabbitmq-c as the oracle end) --------------------------
 *
 * fuzzpub N SEED BASE — publish N confirmed messages to fuzz.queue whose
 *   header tables are random (every field kind librabbitmq encodes,
 *   nested tables/arrays, boundary-length strings) with a planted
 *   x-stream-offset = BASE+i; rabbitmq-c is the ENCODER oracle, the far
 *   side (the in-tree C++ codec) must skip every fuzzed field to find
 *   the planted value.
 * fuzzget N BASE — basic.get N messages from fuzz.queue and DECODE the
 *   properties with librabbitmq (the decoder oracle): a table the
 *   in-tree encoder produced that librabbitmq cannot parse, or whose
 *   planted offset/body disagree, is a codec bug.
 */

static uint64_t fz_state;
static uint64_t fz_next(void) {
  uint64_t z = (fz_state += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

static char fz_arena[262144];
static size_t fz_off;

static void *fz_alloc(size_t n) {
  if (fz_off + n > sizeof fz_arena) {
    fprintf(stderr, "PROBE FAIL: fuzz arena exhausted\n");
    exit(1);
  }
  void *p = fz_arena + fz_off;
  fz_off += n;
  return p;
}

static amqp_bytes_t fz_string(size_t maxlen) {
  size_t n = fz_next() % (maxlen + 1);
  char *p = fz_alloc(n ? n : 1);
  for (size_t i = 0; i < n; ++i) p[i] = (char)(fz_next() & 0xFF);
  amqp_bytes_t b = {n, p};
  return b;
}

static amqp_bytes_t fz_key(void) {
  size_t n = 1 + fz_next() % 20;
  char *p = fz_alloc(n);
  for (size_t i = 0; i < n; ++i) p[i] = 'a' + (char)(fz_next() % 26);
  amqp_bytes_t b = {n, p};
  return b;
}

static void fz_value(amqp_field_value_t *v, int depth) {
  static const char kinds[] = "tbBsuIilfdDSTVFA";
  v->kind = (uint8_t)kinds[fz_next() % (depth > 0 ? 16 : 14)];
  switch (v->kind) {
    case 't': v->value.boolean = (int)(fz_next() & 1); break;
    case 'b': v->value.i8 = (int8_t)fz_next(); break;
    case 'B': v->value.u8 = (uint8_t)fz_next(); break;
    case 's': v->value.i16 = (int16_t)fz_next(); break;
    case 'u': v->value.u16 = (uint16_t)fz_next(); break;
    case 'I': v->value.i32 = (int32_t)fz_next(); break;
    case 'i': v->value.u32 = (uint32_t)fz_next(); break;
    case 'l': v->value.i64 = (int64_t)fz_next(); break;
    case 'f': v->value.f32 = (float)(int32_t)fz_next(); break;
    case 'd': v->value.f64 = (double)(int64_t)fz_next(); break;
    case 'D':
      v->value.decimal.decimals = (uint8_t)(fz_next() % 10);
      v->value.decimal.value = (uint32_t)fz_next();
      break;
    case 'S': v->value.bytes = fz_string(fz_next() % 8 == 0 ? 8192 : 64); break;
    case 'T': v->value.u64 = fz_next(); break;
    case 'V': break;
    case 'F': {
      int n = (int)(fz_next() % 4);
      amqp_table_entry_t *es = fz_alloc(sizeof(amqp_table_entry_t) * (n ? n : 1));
      for (int i = 0; i < n; ++i) {
        es[i].key = fz_key();
        fz_value(&es[i].value, depth - 1);
      }
      v->value.table.num_entries = n;
      v->value.table.entries = es;
      break;
    }
    case 'A': {
      int n = (int)(fz_next() % 4);
      amqp_field_value_t *is = fz_alloc(sizeof(amqp_field_value_t) * (n ? n : 1));
      for (int i = 0; i < n; ++i) fz_value(&is[i], depth - 1);
      v->value.array.num_entries = n;
      v->value.array.entries = is;
      break;
    }
  }
}

static int run_fuzzpub(amqp_connection_state_t c, const char *queue, int n,
                       long long seed, long long base) {
  for (int i = 0; i < n; ++i) {
    fz_state = (uint64_t)seed + (uint64_t)i;
    fz_off = 0;
    int n_fields = (int)(fz_next() % 8);
    int plant_at = (int)(fz_next() % (n_fields + 1));
    amqp_table_entry_t es[9];
    for (int k = 0; k <= n_fields; ++k) {
      if (k == plant_at) {
        es[k].key = amqp_cstring_bytes("x-stream-offset");
        es[k].value.kind = 'l';
        es[k].value.value.i64 = base + i;
      } else {
        es[k].key = fz_key();
        fz_value(&es[k].value, 2);
      }
    }
    amqp_basic_properties_t props;
    memset(&props, 0, sizeof props);
    props._flags = AMQP_BASIC_HEADERS_FLAG;
    props.headers.num_entries = n_fields + 1;
    props.headers.entries = es;
    char buf[16];
    snprintf(buf, sizeof buf, "%d", i);
    int rc = amqp_basic_publish(c, 1, amqp_cstring_bytes(""),
                                amqp_cstring_bytes(queue), 1, 0, &props,
                                amqp_cstring_bytes(buf));
    CHECK(rc == 0, "fuzz publish (librabbitmq encode)");
    amqp_method_t m;
    CHECK(amqp_simple_wait_method(c, 1, AMQP_BASIC_ACK_METHOD, &m) == 0,
          "fuzz publish confirm");
  }
  printf("FUZZPUB OK %d\n", n);
  return 0;
}

static int run_fuzzget(amqp_connection_state_t c, const char *queue, int n,
                       long long base) {
  char *seen = calloc(1, (size_t)n);
  for (int i = 0; i < n; ++i) {
    amqp_maybe_release_buffers(c);
    amqp_rpc_reply_t r = amqp_basic_get(c, 1, amqp_cstring_bytes(queue), 1);
    CHECK_RPC(r, "fuzz basic.get");
    CHECK(r.reply.id == AMQP_BASIC_GET_OK_METHOD, "fuzz get-ok (not empty)");
    amqp_message_t msg;
    r = amqp_read_message(c, 1, &msg, 0);
    CHECK_RPC(r, "fuzz read message (librabbitmq decodes the table)");
    int v = body_int(msg.body);
    CHECK(v >= 0 && v < n && !seen[v], "fuzz body unique+known");
    seen[v] = 1;
    CHECK(msg.properties._flags & AMQP_BASIC_HEADERS_FLAG,
          "fuzz message carries headers");
    amqp_table_t *h = &msg.properties.headers;
    amqp_table_entry_t *es = (amqp_table_entry_t *)h->entries;
    int found = 0;
    for (int k = 0; k < h->num_entries; ++k) {
      if (es[k].key.len == 15 &&
          memcmp(es[k].key.bytes, "x-stream-offset", 15) == 0) {
        CHECK(es[k].value.kind == 'l', "fuzz planted kind");
        CHECK(es[k].value.value.i64 == base + v, "fuzz planted value");
        found = 1;
      }
    }
    CHECK(found, "fuzz planted key survived the junk fields");
    amqp_destroy_message(&msg);
  }
  free(seen);
  printf("FUZZGET OK %d\n", n);
  return 0;
}

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: interop_probe HOST PORT [tx] [stream] |"
            " fuzzpub N SEED BASE | fuzzget N BASE\n");
    return 2;
  }
  const char *host = argv[1];
  int port = atoi(argv[2]);
  int with_tx = 0, with_stream = 0;
  for (int i = 3; i < argc; ++i) {
    if (strcmp(argv[i], "tx") == 0) with_tx = 1;
    if (strcmp(argv[i], "stream") == 0) with_stream = 1;
  }
  if (argc >= 5 && (strcmp(argv[3], "fuzzpub") == 0 ||
                    strcmp(argv[3], "fuzzget") == 0)) {
    amqp_connection_state_t fc = amqp_new_connection();
    amqp_socket_t *fsock = amqp_tcp_socket_new(fc);
    CHECK(fsock != NULL, "tcp socket");
    CHECK(amqp_socket_open(fsock, host, port) == 0, "connect");
    amqp_rpc_reply_t fr = amqp_login(fc, "/", 0, 131072, 0,
                                     AMQP_SASL_METHOD_PLAIN, "guest",
                                     "guest");
    CHECK_RPC(fr, "login");
    amqp_channel_open(fc, 1);
    CHECK_RPC(amqp_get_rpc_reply(fc), "channel.open");
    amqp_queue_declare(fc, 1, amqp_cstring_bytes("fuzz.queue"), 0, 1, 0, 0,
                       amqp_empty_table);
    CHECK_RPC(amqp_get_rpc_reply(fc), "queue.declare");
    int rc;
    if (strcmp(argv[3], "fuzzpub") == 0) {
      CHECK(argc >= 6, "fuzzpub needs N SEED BASE");
      amqp_confirm_select(fc, 1);
      CHECK_RPC(amqp_get_rpc_reply(fc), "confirm.select");
      rc = run_fuzzpub(fc, "fuzz.queue", atoi(argv[4]), atoll(argv[5]),
                       argc >= 7 ? atoll(argv[6]) : 0);
    } else {
      CHECK(argc >= 6, "fuzzget needs N BASE");
      rc = run_fuzzget(fc, "fuzz.queue", atoi(argv[4]), atoll(argv[5]));
    }
    amqp_destroy_connection(fc);
    return rc;
  }
  const char *queue = "probe.queue";

  amqp_connection_state_t c = amqp_new_connection();
  amqp_socket_t *sock = amqp_tcp_socket_new(c);
  CHECK(sock != NULL, "tcp socket");
  CHECK(amqp_socket_open(sock, host, port) == 0, "connect");
  amqp_rpc_reply_t r =
      amqp_login(c, "/", 0, 131072, 0, AMQP_SASL_METHOD_PLAIN, "guest",
                 "guest");
  CHECK_RPC(r, "login (handshake: start/tune/open)");

  amqp_channel_open(c, 1);
  CHECK_RPC(amqp_get_rpc_reply(c), "channel.open");
  amqp_queue_declare(c, 1, amqp_cstring_bytes(queue), 0, 1, 0, 0,
                     amqp_empty_table);
  CHECK_RPC(amqp_get_rpc_reply(c), "queue.declare");
  amqp_confirm_select(c, 1);
  CHECK_RPC(amqp_get_rpc_reply(c), "confirm.select");

  int seen[2 * N_MSGS] = {0};

  /* publisher-confirmed publishes */
  for (int v = 0; v < N_MSGS; ++v)
    CHECK(publish_one(c, queue, v, 1) == 0, "publish+confirm");

  /* polling reads: basic.get until get-empty */
  int got = 0;
  for (;;) {
    amqp_maybe_release_buffers(c);
    r = amqp_basic_get(c, 1, amqp_cstring_bytes(queue), 1);
    CHECK_RPC(r, "basic.get");
    if (r.reply.id == AMQP_BASIC_GET_EMPTY_METHOD) break;
    CHECK(r.reply.id == AMQP_BASIC_GET_OK_METHOD, "get-ok method id");
    amqp_message_t msg;
    r = amqp_read_message(c, 1, &msg, 0);
    CHECK_RPC(r, "read message (header+body frames)");
    int v = body_int(msg.body);
    CHECK(v >= 0 && v < N_MSGS && !seen[v], "get value unique+known");
    seen[v] = 1;
    ++got;
    amqp_destroy_message(&msg);
  }
  CHECK(got == N_MSGS, "all published values read back via basic.get");

  /* push consume: basic.consume + deliver */
  for (int v = 0; v < N_MSGS; ++v)
    CHECK(publish_one(c, queue, N_MSGS + v, 1) == 0, "publish round 2");
  amqp_basic_consume(c, 1, amqp_cstring_bytes(queue), amqp_empty_bytes, 0,
                     1, 0, amqp_empty_table);
  CHECK_RPC(amqp_get_rpc_reply(c), "basic.consume");
  for (int i = 0; i < N_MSGS; ++i) {
    amqp_envelope_t env;
    struct timeval tv = {5, 0};
    amqp_maybe_release_buffers(c);
    r = amqp_consume_message(c, &env, &tv, 0);
    CHECK_RPC(r, "consume (basic.deliver + content)");
    int v = body_int(env.message.body);
    CHECK(v >= N_MSGS && v < 2 * N_MSGS && !seen[v], "deliver value");
    seen[v] = 1;
    amqp_destroy_envelope(&env);
  }

  if (with_stream) {
    /* stream subset on its own channel — confirm mode, the delivery-tag
       sequence, and the ack channel are per-channel (spec), so a second
       channel with its own confirm.select exercises exactly the paths a
       channel-1-only probe would leave dead: x-queue-type table arg on
       declare, confirmed publishes whose acks ride channel 2,
       x-stream-offset table arg on consume, in-order replay from offset
       0, offset headers parsed by rabbitmq-c's own table decoder */
    const char *squeue = "probe.stream";
    amqp_channel_open(c, 2);
    CHECK_RPC(amqp_get_rpc_reply(c), "channel.open (2)");

    amqp_table_entry_t decl_e[1];
    decl_e[0].key = amqp_cstring_bytes("x-queue-type");
    decl_e[0].value.kind = 'S';
    decl_e[0].value.value.bytes = amqp_cstring_bytes("stream");
    amqp_table_t decl_args = {1, decl_e};
    amqp_queue_declare(c, 2, amqp_cstring_bytes(squeue), 0, 1, 0, 0,
                       decl_args);
    CHECK_RPC(amqp_get_rpc_reply(c), "stream queue.declare (table arg)");

    amqp_confirm_select(c, 2);
    CHECK_RPC(amqp_get_rpc_reply(c), "confirm.select (channel 2)");
    for (int v = 0; v < N_MSGS; ++v)
      CHECK(publish_one_ch(c, 2, squeue, v, 1) == 0,
            "stream publish + channel-2 confirm");

    amqp_table_entry_t cons_e[1];
    cons_e[0].key = amqp_cstring_bytes("x-stream-offset");
    cons_e[0].value.kind = 'l';
    cons_e[0].value.value.i64 = 0;
    amqp_table_t cons_args = {1, cons_e};
    amqp_basic_consume(c, 2, amqp_cstring_bytes(squeue), amqp_empty_bytes,
                       0, 1, 0, cons_args);
    CHECK_RPC(amqp_get_rpc_reply(c),
              "stream basic.consume (x-stream-offset arg)");

    for (int i = 0; i < N_MSGS; ++i) {
      amqp_envelope_t env;
      struct timeval tv = {5, 0};
      amqp_maybe_release_buffers(c);
      r = amqp_consume_message(c, &env, &tv, 0);
      CHECK_RPC(r, "stream consume (deliver + content)");
      CHECK(body_int(env.message.body) == i,
            "stream replay in append order from offset 0");
      CHECK(env.message.properties._flags & AMQP_BASIC_HEADERS_FLAG,
            "stream delivery carries a headers table");
      amqp_table_t *h = &env.message.properties.headers;
      amqp_table_entry_t *es = (amqp_table_entry_t *)h->entries;
      int found = 0;
      for (int k = 0; k < h->num_entries; ++k) {
        if (es[k].key.len == 15 &&
            memcmp(es[k].key.bytes, "x-stream-offset", 15) == 0) {
          CHECK(es[k].value.kind == 'l', "offset header kind is int64");
          CHECK(es[k].value.value.i64 == i, "offset header value");
          found = 1;
        }
      }
      CHECK(found, "x-stream-offset header present");
      amqp_destroy_envelope(&env);
    }
  }

  if (with_tx) {
    /* tx class: committed publish is visible, rolled-back one is not */
    amqp_tx_select(c, 1);
    CHECK_RPC(amqp_get_rpc_reply(c), "tx.select");
    CHECK(publish_one(c, queue, 7777, 0) == 0, "tx publish");
    amqp_tx_rollback(c, 1);
    CHECK_RPC(amqp_get_rpc_reply(c), "tx.rollback");
    CHECK(publish_one(c, queue, 8888, 0) == 0, "tx publish 2");
    amqp_tx_commit(c, 1);
    CHECK_RPC(amqp_get_rpc_reply(c), "tx.commit");
    amqp_envelope_t env;
    struct timeval tv = {5, 0};
    amqp_maybe_release_buffers(c);
    r = amqp_consume_message(c, &env, &tv, 0);
    CHECK_RPC(r, "consume committed tx message");
    CHECK(body_int(env.message.body) == 8888,
          "rollback invisible, commit visible");
    amqp_destroy_envelope(&env);
  }

  printf("PROBE OK: handshake, declare, %d confirmed publishes, "
         "%d gets, %d delivers%s%s\n",
         (2 + with_stream) * N_MSGS, N_MSGS, N_MSGS,
         with_tx ? ", tx" : "", with_stream ? ", stream" : "");
  amqp_destroy_connection(c);
  return 0;
}
