#!/bin/sh
# Provision a broker node: sshd + the control plane's public key
# (reference twin: docker/shared/init-node.sh).
set -eu

if [ -f /root/.node-provisioned ]; then exit 0; fi

apt-get update -y
DEBIAN_FRONTEND=noninteractive apt-get install -y \
    openssh-server wget xz-utils iptables procps psmisc

mkdir -p /run/sshd /root/.ssh
while [ ! -f /root/shared/jepsen-bot.pub ]; do sleep 1; done
cat /root/shared/jepsen-bot.pub >> /root/.ssh/authorized_keys
chmod 600 /root/.ssh/authorized_keys
/usr/sbin/sshd

touch /root/.node-provisioned
