#!/bin/sh
# Provision the control container: framework runtime (CPU JAX), toolchain
# for the native driver, and an SSH keypair for the control plane
# (reference twin: docker/shared/init-control.sh — jdk/lein/gnuplot there,
# python/jax/g++ here).
set -eu

if [ -f /root/.control-provisioned ]; then exit 0; fi

apt-get update -y
DEBIAN_FRONTEND=noninteractive apt-get install -y \
    python3 python3-pip python3-venv g++ make openssh-client wget

python3 -m venv /root/venv
. /root/venv/bin/activate
pip install -q jax matplotlib numpy pytest

make -C /root/jepsen-tpu/native

if [ ! -f /root/shared/jepsen-bot ]; then
    ssh-keygen -t ed25519 -N "" -f /root/shared/jepsen-bot
fi

touch /root/.control-provisioned
echo "control provisioned; run tests with:"
echo "  . /root/venv/bin/activate && cd /root/jepsen-tpu && \\"
echo "  python -m jepsen_tpu test --db rabbitmq --nodes n1,n2,n3 \\"
echo "      --ssh-private-key /root/shared/jepsen-bot --time-limit 30"
