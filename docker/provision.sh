#!/bin/sh
# Bring the topology up and wait for provisioning (reference twin:
# docker/provision.sh).
set -eu
cd "$(dirname "$0")"
docker compose up -d
for c in jepsen-tpu-control jepsen-tpu-n1 jepsen-tpu-n2 jepsen-tpu-n3; do
    echo "waiting for $c..."
    docker exec "$c" sh -c 'while [ ! -f /root/.control-provisioned ] && [ ! -f /root/.node-provisioned ]; do sleep 2; done'
done
echo "topology ready"
