# CI image for the TPU-native Jepsen harness.
#
# This container only *drives* a cluster: it needs terraform + awscli to
# provision, ssh/git/python to run the matrix orchestration, and nothing
# else — Erlang and RabbitMQ live on the provisioned workers, JAX/TPU on
# the controller.  (The reference's CI image additionally bakes a pinned
# Erlang; here that pin ships as an apt preference pushed to workers by
# the DB lifecycle instead.)

FROM debian:bookworm

ARG TERRAFORM_VERSION=1.15.8
ENV LANG=C.UTF-8

RUN set -eux; \
    apt-get update; \
    apt-get upgrade -y; \
    apt-get install -y --no-install-recommends \
        apt-transport-https ca-certificates curl git gnupg lsb-release \
        make openssh-client python3 python3-pip python3-venv unzip wget; \
    rm -rf /var/lib/apt/lists/*

# awscli v2 (store/broker-log archival to S3) and terraform (cluster
# provisioning), both verified by running their version commands
RUN set -eux; \
    curl -fsSL "https://awscli.amazonaws.com/awscli-exe-linux-x86_64.zip" \
        -o /tmp/awscli.zip; \
    unzip -q /tmp/awscli.zip -d /tmp; \
    /tmp/aws/install; \
    rm -rf /tmp/awscli.zip /tmp/aws; \
    aws --version; \
    curl -fsSL "https://releases.hashicorp.com/terraform/${TERRAFORM_VERSION}/terraform_${TERRAFORM_VERSION}_linux_amd64.zip" \
        -o /tmp/terraform.zip; \
    unzip -q /tmp/terraform.zip -d /tmp/terraform; \
    install -m 0755 /tmp/terraform/terraform /usr/bin/terraform; \
    rm -rf /tmp/terraform.zip /tmp/terraform; \
    terraform version
