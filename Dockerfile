# CI image for the TPU-native Jepsen harness (equivalent of the
# reference's Dockerfile, which ships terraform + awscli + a pinned Erlang
# for its CI container).  This image only *drives* the cluster — terraform,
# awscli, ssh, and a python with the framework's host-side deps; Erlang and
# RabbitMQ live on the provisioned workers, JAX/TPU on the controller.

FROM debian:bookworm

ENV LANG='C.UTF-8'
ENV TERRAFORM_VERSION='1.15.8'

RUN apt-get clean && \
    apt-get update && \
    apt-get -y upgrade && \
    apt-get install -y -V --no-install-recommends \
      ca-certificates \
      apt-transport-https \
      gnupg \
      wget \
      curl \
      openssh-client \
      unzip \
      lsb-release \
      make \
      git \
      python3 \
      python3-pip \
      python3-venv

RUN curl "https://awscli.amazonaws.com/awscli-exe-linux-x86_64.zip" -o "awscliv2.zip" && \
    unzip awscliv2.zip && \
    ./aws/install && \
    rm awscliv2.zip && \
    rm -rf ./aws && \
    aws --version

RUN wget https://releases.hashicorp.com/terraform/${TERRAFORM_VERSION}/terraform_${TERRAFORM_VERSION}_linux_amd64.zip && \
    unzip terraform_${TERRAFORM_VERSION}_linux_amd64.zip && \
    mv terraform /usr/bin && \
    chmod u+x /usr/bin/terraform && \
    rm terraform_${TERRAFORM_VERSION}_linux_amd64.zip && \
    terraform version
