"""Long-soak driver: N-minute mixed-nemesis durable soaks for every
workload family (VERDICT #4 "longer soaks") under the ``tests/_live.py``
triage supervisor, with fail-loud artifact capture.

Round-7 review found a supervisor tee-ing ``python tools/soak.py``'s
*file-not-found error* into ``store/`` evidence files — a failed
invocation masquerading as green soak evidence.  This module is that
missing entry point, and it closes the hole structurally: with
``--out``, the log is teed to a temp file and only renamed into place
when the run reached its expected verdict.  A crash, a wrong verdict,
or triage exhaustion exits non-zero and leaves ``PATH.failed`` —
never a committed-looking artifact.

How the r7 evidence pair was produced::

    python tools/soak.py --workload mutex --fenced --minutes 30 \
        --out store/soak_r7_30min_5node_mutex_fenced_supervised.txt
    python tools/soak.py --workload queue --minutes 30 \
        --out store/soak_r7_30min_5node_queue.txt

The mutex run captured its artifact (green, one attempt).  The queue
run exited 1 with only ``...queue.txt.failed`` — the durable queue
lost acked messages on both triage attempts; that log was renamed to
``store/soak_r7_30min_5node_queue_red.txt`` and indexed in PARITY.md
as an open finding.  Expect the queue recipe to keep failing until
the loss is fixed.

Exit code 0 = the run reached its expected verdict under the triage
rules (and the artifact, if requested, was captured); non-zero = it
never did within ``--attempts``, and no artifact was written.

Substrate note (PR 7): the recorded history lands with its ``.jtc``
columnar sibling (``Store.save_history`` → COLUMNAR.md), and the
pipelined post-run analysis (``attach_pipelined_checkers`` →
``check_sources``) consumes it through the unified cache loaders — a
soak's verdict pass and any later re-check map bytes straight into
staging buffers with no JSONL re-parse.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

WORKLOADS = ("queue", "mutex", "stream", "elle")


class _Tee:
    """Mirror writes to every underlying stream (console + artifact)."""

    def __init__(self, *streams):
        self._streams = streams

    def write(self, s):
        for st in self._streams:
            st.write(s)
        return len(s)

    def flush(self):
        for st in self._streams:
            st.flush()


def capture(out_path: str, fn) -> int:
    """Fail-loud artifact capture around ``fn() -> int``.

    stdout/stderr are teed into a temp file beside ``out_path`` while
    ``fn`` runs.  Only a 0 return renames the log into place; any other
    return or an exception keeps it at ``out_path + ".failed"`` and
    propagates a non-zero exit — the artifact directory never gains a
    green-looking file from a failed invocation.
    """
    d = os.path.dirname(os.path.abspath(out_path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(out_path) + ".", suffix=".tmp", dir=d
    )
    # mkstemp's 0600 would survive os.replace — evidence files must be
    # world-readable like every other store/ artifact
    os.fchmod(fd, 0o644)
    rc = 1
    interrupted = False
    old_out, old_err = sys.stdout, sys.stderr
    with os.fdopen(fd, "w") as f:
        sys.stdout = _Tee(old_out, f)
        sys.stderr = _Tee(old_err, f)
        try:
            try:
                rc = fn()
                if not isinstance(rc, int) or isinstance(rc, bool):
                    # a bare/odd return — including True/False, which
                    # ARE ints — must not reach sys.exit(None)/
                    # sys.exit(False) (exit code 0!) after the log
                    # went to .failed
                    rc = 1
            except SystemExit as e:
                # only an explicit non-bool int code carries through;
                # a bare sys.exit(), sys.exit("message"), or
                # sys.exit(False) from a library fatal path is a
                # failure — it must never mint an artifact
                explicit = isinstance(e.code, int) and not isinstance(
                    e.code, bool
                )
                rc = e.code if explicit else 1
                if not explicit and e.code is not None:
                    print(f"soak: SystemExit: {e.code}", file=sys.stderr)
            except KeyboardInterrupt:
                # routed to .failed like any failure, then re-raised
                # after cleanup: the operator's Ctrl-C must still kill
                # the process with the interrupt status, so a
                # supervisor retrying on "run failed" doesn't relaunch
                # a run the operator was stopping
                traceback.print_exc()
                rc = 1
                interrupted = True
            except BaseException:
                traceback.print_exc()
                rc = 1
        finally:
            out_tee, err_tee = sys.stdout, sys.stderr
            sys.stdout, sys.stderr = old_out, old_err
            # run_soak's basicConfig(stream=sys.stdout) bound the root
            # handler to the tee; rebind before the file closes so
            # stray daemon-thread log records (unjoined cluster
            # threads) don't hit a dead stream — each tee back onto
            # the stream it wrapped, so stderr records stay on stderr
            for h in logging.root.handlers:
                if getattr(h, "stream", None) is out_tee:
                    h.stream = old_out
                elif getattr(h, "stream", None) is err_tee:
                    h.stream = old_err
    if rc == 0:
        os.replace(tmp, out_path)
    else:
        failed = out_path + ".failed"
        os.replace(tmp, failed)
        print(
            f"soak: run failed (rc={rc}); artifact NOT captured; "
            f"log kept at {failed}",
            file=sys.stderr,
        )
    if interrupted:
        raise KeyboardInterrupt
    return rc


def run_soak(args) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        stream=sys.stdout,
        force=True,
    )

    from _live import run_live_with_triage

    from jepsen_tpu.checkers.live import attach_live_monitor_for
    from jepsen_tpu.client import native as native_mod
    from jepsen_tpu.harness.localcluster import build_local_test
    from jepsen_tpu.history.store import _json_default

    store = args.store or tempfile.mkdtemp(prefix=f"soak_{args.workload}_")
    opts = {
        "rate": args.rate,
        "time-limit": args.minutes * 60.0,
        "time-before-partition": 2.0,
        "partition-duration": 10.0,
        "network-partition": "partition-random-halves",
        "nemesis": "mixed",
        "recovery-sleep": 20.0,
        "publish-confirm-timeout": 5.0,
        "durable": True,
        "seed": args.seed,
        "mixed-extended": args.mixed_extended,
    }
    monitor_name = args.workload
    if args.workload == "mutex":
        opts["fenced"] = args.fenced
        if args.fenced:
            monitor_name = "fenced-mutex"
    print(
        f"# soak: {args.workload}"
        f"{' (fenced)' if args.workload == 'mutex' and args.fenced else ''},"
        f" {args.nodes} nodes, {args.minutes:g} min mixed nemesis,"
        f" durable, seed={args.seed}, expect={args.expect}",
        flush=True,
    )

    from jepsen_tpu.obs import trace as obs_trace

    if args.trace_out:
        obs_trace.enable()
        print(f"# soak: flight recorder on -> {args.trace_out}", flush=True)

    monitors = []
    live_checkers = []
    live_tailers = []

    def build():
        native_mod.reset()
        test, transport = build_local_test(
            opts,
            n_nodes=args.nodes,
            concurrency=args.nodes,
            checker_backend="cpu",
            store_root=store,
            workload=args.workload,
            durable=True,
        )
        if not args.serial:
            # post-run analysis through the bytes-to-verdict pipeline
            # executor (parallel/pipeline.py): the stored history.jsonl
            # is packed by the native thread pool and checked on device,
            # instead of re-packing 100k+ Op objects on one thread —
            # identical verdict content (tests/test_pipeline.py), less
            # soak wall time spent in the analysis phase
            from jepsen_tpu.parallel.pipeline import (
                attach_pipelined_checkers,
            )

            # --lanes on a soak means "scale the analysis out": the
            # run has ONE history file, so the scale-out axis is the
            # op axis — mesh=True resolves (at check time) to a
            # seq-parallel mesh over all local devices for the
            # queue/stream families (PipelinedChecker._resolved_opts)
            scale = {"mesh": True} if args.lanes is not None else {}
            if getattr(args, "fail_fast", False):
                # the triage escape hatch: any analysis-stage failure
                # aborts loudly (PipelineError) instead of quarantining
                scale["fail_fast"] = True
            if attach_pipelined_checkers(
                test, args.workload, lanes=args.lanes, **scale
            ):
                note = (
                    " (seq-meshed over local devices)" if scale else ""
                )
                print(f"# soak: pipelined analysis{note} (pass "
                      "--serial for the classic single-thread checkers)",
                      flush=True)
        monitors.append(attach_live_monitor_for(test, monitor_name))
        if args.live_check:
            # segmented online checking ON the recording stream
            # (SEGMENTED.md): an observer on the run recorder feeds
            # full segments to the carry engine on a worker thread and
            # reports record-to-verdict latency via the PR-9 sketches
            from jepsen_tpu.checkers.segmented import LiveSegmentChecker

            lc = LiveSegmentChecker(
                args.workload,
                args.live_check,
                opts=(
                    {"delivery": "at-least-once"}
                    if args.workload == "queue"
                    else {"append_fail": "indeterminate"}
                    if args.workload == "stream"
                    else {"model": "read-committed"}
                    if args.workload == "elle"
                    else {}
                ),
            )
            test.observers.append(lc)
            live_checkers.append(lc)
        if args.live_stream:
            # live tailing (ISSUE 17): the run's op blocks go straight
            # into the checker SERVICE as they are recorded (no
            # recorded-file intermediary) and verdict windows come BACK
            # pushed over the subscription surface — the full
            # record -> stream -> verdict loop closed on a live run
            from jepsen_tpu.campaign.tail import LiveStreamTailer

            host, _, port = args.live_stream.rpartition(":")
            tailer = LiveStreamTailer(
                host or "127.0.0.1",
                int(port),
                args.workload,
                opts=(
                    {"delivery": "at-least-once"}
                    if args.workload == "queue"
                    else {"append_fail": "indeterminate"}
                    if args.workload == "stream"
                    else {"model": "read-committed"}
                    if args.workload == "elle"
                    else {}
                ),
                block_ops=args.live_stream_block,
            )
            print(f"# soak: live-tailing into {args.live_stream} "
                  f"(stream {tailer.sid}, {args.live_stream_block} "
                  f"ops/block)", flush=True)
            test.observers.append(tailer)
            live_tailers.append(tailer)
        return test, transport

    t0 = time.monotonic()
    try:
        with obs_trace.span(
            "soak.run",
            track="soak",
            args=(
                {"workload": args.workload, "minutes": args.minutes,
                 "nodes": args.nodes, "seed": args.seed}
                if obs_trace.is_enabled()
                else None
            ),
        ):
            run = run_live_with_triage(
                build, expect=args.expect, max_attempts=args.attempts
            )
    except AssertionError as e:
        print(f"# soak FAILED to reach expect={args.expect}: {e}", flush=True)
        return 1
    wall = time.monotonic() - t0
    if monitors and monitors[-1] is not None:
        snap = monitors[-1].snapshot()
        counts = ", ".join(f"{v} {k}" for k, v in snap["anomalies"].items())
        print(
            f"# live monitor ({monitors[-1].name}): {counts} "
            f"(of {snap['observations']} observations); "
            f"violation-so-far={snap['violation-so-far']}",
            flush=True,
        )
    print(json.dumps(run.results, indent=1, default=_json_default))
    # latency sketch percentiles (ISSUE-11 satellite): the wall clock
    # alone says nothing about what the RUN felt like — print the op
    # completion latency and the analysis check-batch latency off the
    # PR-9 quantile sketches
    from jepsen_tpu.history.rows import _rows_for
    from jepsen_tpu.obs.metrics import REGISTRY, QuantileSketch

    op_sketch = QuantileSketch()
    rows = _rows_for(run.history)
    for lat in rows[(rows[:, 7] == 1) & (rows[:, 6] >= 0), 6]:
        op_sketch.add(float(lat))

    def _pq(s, q, scale=1.0):
        v = s.quantile(q)
        return "-" if v != v else f"{v * scale:.1f}"

    check_sketch = REGISTRY.sketch("pipeline.check_batch_s")
    print(
        f"# soak latency sketches: op p50 {_pq(op_sketch, 0.5)}ms / "
        f"p99 {_pq(op_sketch, 0.99)}ms "
        f"({op_sketch.count} completions); analysis check-batch "
        f"p50 {_pq(check_sketch, 0.5, 1e3)}ms / "
        f"p99 {_pq(check_sketch, 0.99, 1e3)}ms "
        f"({check_sketch.count} batches)",
        flush=True,
    )
    # live-check summary (ISSUE 15): record-to-verdict latency off the
    # segmented engine's sketch, printed BESIDE the op-latency line —
    # fail-loud below if live mode produced no verdict windows
    live_summary = None
    if args.live_check and live_checkers:
        live_summary = live_checkers[-1].close()
        print(
            f"# soak live-check: {live_summary['windows']} verdict "
            f"windows over {live_summary['ops']} recorded ops "
            f"(segment={args.live_check}); record-to-verdict "
            f"p50 {live_summary['p50_ms']:.1f}ms / "
            f"p99 {live_summary['p99_ms']:.1f}ms "
            f"({live_summary['samples']} op samples); "
            f"live verdict-so-far={live_summary['verdict']}",
            flush=True,
        )
        if live_summary.get("saturated_at_op") is not None:
            print(
                f"# soak live-check SATURATED at op "
                f"{live_summary['saturated_at_op']}: the checker "
                f"could not keep up with the recorder — "
                f"{live_summary['ops_unverified']} ops went "
                f"unverified live (post-run analysis still covers "
                f"them)",
                flush=True,
            )
        if live_summary["errors"]:
            print(
                f"# soak live-check ERRORS: {live_summary['errors']}",
                flush=True,
            )
    # live-stream summary (ISSUE 17): the service's pushed verdict
    # windows beside the in-process live-check line — fail-loud below
    # if the loop never closed (zero pushed windows, or tail errors)
    tail_summary = None
    if args.live_stream and live_tailers:
        tail_summary = live_tailers[-1].close()
        p50 = tail_summary["record_to_verdict_p50_ms"]
        p99 = tail_summary["record_to_verdict_p99_ms"]
        print(
            f"# soak live-stream: {tail_summary['windows_pushed']} "
            f"verdict windows PUSHED over "
            f"{tail_summary['blocks_fed']} fed blocks "
            f"({tail_summary['ops_fed']}/{tail_summary['ops']} ops); "
            f"record-to-verdict "
            f"p50 {p50 if p50 is not None else '-'}ms / "
            f"p99 {p99 if p99 is not None else '-'}ms "
            f"({tail_summary['latency_samples']} block samples); "
            f"service verdict={tail_summary['verdict']}",
            flush=True,
        )
        if tail_summary.get("saturated_at_op") is not None:
            print(
                f"# soak live-stream SATURATED at op "
                f"{tail_summary['saturated_at_op']}: the service could "
                f"not keep up — {tail_summary['ops_unverified']} ops "
                f"went unverified live (post-run analysis still covers "
                f"them)",
                flush=True,
            )
        if tail_summary["errors"]:
            print(
                f"# soak live-stream ERRORS: {tail_summary['errors']}",
                flush=True,
            )
    # elastic-analysis honesty line (ISSUE 13): a quarantined chunk in
    # the analysis phase means part of THIS soak's history went
    # unjudged — that must never hide inside a wall-clock summary
    n_retries = int(REGISTRY.value("pipeline.unit_retries"))
    n_quar = int(REGISTRY.value("pipeline.quarantined"))
    if n_retries or n_quar:
        print(
            f"# soak elastic analysis: {n_retries} unit retries, "
            f"{n_quar} QUARANTINED histories (explicit unknowns — "
            f"re-run with --serial or --fail-fast to triage)",
            flush=True,
        )
    # cluster telemetry summary (ISSUE 12): the SUT's own internals —
    # who led, how many elections, tripwire count — beside the
    # checker-side sketches above
    if run.run_dir is not None:
        from jepsen_tpu.obs.cluster import load_cluster_json, summary_line

        cdoc = load_cluster_json(run.run_dir)
        if cdoc is not None:
            print(
                f"# soak cluster telemetry: {summary_line(cdoc)}",
                flush=True,
            )
    print(
        f"# soak done in {wall:.0f}s wall ({len(run.history)} history "
        f"ops, attempts logged above)",
        flush=True,
    )
    if run.results.get("valid?") is True:
        print("Everything looks good! ヽ('ー`)ノ")
    else:
        print("Analysis invalid! ಠ~ಠ")
    if args.live_check and (
        live_summary is None
        or live_summary["windows"] == 0
        or live_summary["errors"]
    ):
        # fail-loud: a live-check soak whose live engine never produced
        # a verdict window (or crashed) must not mint a green artifact
        print(
            "# soak live-check FAILED: no verdict windows "
            f"(summary={live_summary})",
            flush=True,
        )
        return 1
    if args.live_stream and (
        tail_summary is None
        or tail_summary["windows_pushed"] == 0
        or tail_summary["errors"]
    ):
        # fail-loud: a live-stream soak that never saw a PUSHED window
        # (or whose tail errored) must not mint a green artifact
        print(
            "# soak live-stream FAILED: loop never closed "
            f"(summary={tail_summary})",
            flush=True,
        )
        return 1
    # triage guarantees the run reached the EXPECTED verdict — only now
    # may the trace artifact land (the --out capture discipline)
    if args.trace_out:
        from jepsen_tpu.obs import export as obs_export

        summary = obs_export.write_trace(args.trace_out)
        print(f"# soak trace: {json.dumps(summary)}", flush=True)
    if getattr(args, "report", False) and run.run_dir is not None:
        # the per-run report beside the captured log: re-rendered here
        # (the runner's default-on pass has no trace link) with the
        # trace artifact cross-linked on the run's own clock
        from jepsen_tpu.report.render import render_run_report

        trace_rel = (
            os.path.relpath(
                os.path.abspath(args.trace_out), run.run_dir
            )
            if args.trace_out
            else None
        )
        paths = render_run_report(
            run.run_dir,
            history=run.history,
            results=run.results,
            trace_path=trace_rel,
        )
        print(
            "# soak report: " + " ".join(sorted(paths.values())),
            flush=True,
        )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workload", required=True, choices=WORKLOADS)
    p.add_argument("--minutes", type=float, default=30.0)
    p.add_argument("--nodes", type=int, default=5)
    p.add_argument("--seed", type=int, default=7,
                   help="nemesis schedule seed")
    p.add_argument("--rate", type=float, default=40.0)
    p.add_argument("--mixed-extended", action="store_true",
                   help="add the slow-disk and wire-chaos families to "
                        "the mixed-nemesis draw (opt-in so default "
                        "soak schedules stay comparable with the "
                        "committed r7/r8 evidence)")
    p.add_argument("--fenced", action="store_true",
                   help="mutex only: fencing-token lock mode (the "
                        "configuration whose soak must stay green)")
    p.add_argument("--expect", choices=("valid", "invalid"),
                   default="valid",
                   help="triage expectation (invalid for runs that "
                        "exercise a documented hazard, e.g. the "
                        "unfenced mutex)")
    p.add_argument("--attempts", type=int, default=2,
                   help="triage attempts (fresh cluster each)")
    p.add_argument("--fail-fast", dest="fail_fast", action="store_true",
                   help="disable the elastic per-chunk quarantine in "
                        "the pipelined analysis: any stage failure "
                        "aborts loudly with no verdicts (the pre-PR-13 "
                        "contract — the triage escape hatch)")
    p.add_argument("--serial", action="store_true",
                   help="triage escape hatch: run the post-run analysis "
                        "on the classic single-thread checkers instead "
                        "of the bytes-to-verdict pipeline executor")
    p.add_argument("--live-check", dest="live_check", type=int,
                   default=None, metavar="N",
                   help="segmented ONLINE checking during the run "
                        "(SEGMENTED.md): tail the recording stream N "
                        "ops at a time through the segmented carry "
                        "engine and print record-to-verdict latency "
                        "p50/p99 (PR-9 sketches) in the triage "
                        "summary; fail-loud if no verdict window was "
                        "ever produced.  Live contracts: at-least-once "
                        "delivery / indeterminate appends / "
                        "read-committed — the levels live SUT runs "
                        "are judged at")
    p.add_argument("--live-stream", dest="live_stream", default=None,
                   metavar="HOST:PORT",
                   help="tail the run's op blocks STRAIGHT into a "
                        "running checker service (jepsen-tpu "
                        "serve-checker) as they are recorded — no "
                        "recorded-file intermediary — and subscribe to "
                        "its pushed verdict windows; prints "
                        "record-to-verdict p50/p99 and fails loud if "
                        "zero windows were ever pushed.  Same live "
                        "contracts as --live-check")
    p.add_argument("--live-stream-block", dest="live_stream_block",
                   type=int, default=32, metavar="N",
                   help="ops per tailed block on the wire "
                        "(--live-stream)")
    p.add_argument("--lanes", type=int, default=None,
                   help="scale the post-run analysis out across local "
                        "devices: the soak's single long history checks "
                        "through a seq-parallel mesh (op axis sharded, "
                        "queue/stream families), with N input lanes for "
                        "any multi-file re-checks (0 = one per device; "
                        "default: the classic single-lane executor)")
    p.add_argument("--store", default=None,
                   help="store root (default: a temp dir)")
    p.add_argument("--out", default=None,
                   help="evidence file to capture the log into; only "
                        "written when the run reaches its expected "
                        "verdict (failure leaves OUT.failed and a "
                        "non-zero exit)")
    p.add_argument("--report", action="store_true",
                   help="emit the per-run report artifacts "
                        "(report.html/timeline.html, trace "
                        "cross-linked) into the run dir beside "
                        "--out/--trace-out — same capture discipline: "
                        "only after the expected verdict")
    p.add_argument("--trace-out", default=None,
                   help="record the soak through the flight recorder "
                        "(jepsen_tpu/obs) and export a Perfetto trace "
                        "here — same capture discipline as --out: the "
                        "artifact lands only when the run reached its "
                        "expected verdict")
    args = p.parse_args(argv)
    if args.fenced and args.workload != "mutex":
        p.error("--fenced only applies to --workload mutex")
    if args.workload == "mutex" and not args.fenced \
            and args.expect == "valid":
        p.error("unfenced mutex soaks green only by luck — the "
                "documented hazard expects invalid; pass --fenced "
                "or --expect invalid explicitly")
    if args.out is None:
        return run_soak(args)
    return capture(args.out, lambda: run_soak(args))


if __name__ == "__main__":
    sys.exit(main())
