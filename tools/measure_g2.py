"""Measure the G2 (anti-dependency cycle) rate of the LIVE elle workload.

The live AMQP-tx mapping promises atomic commit visibility — read
committed — and the elle checker holds it to exactly that level
(``checkers/elle.py``; the round-3 design: check what the SUT claims).
G2 cycles are *admitted* at that level but always *reported*; this tool
turns the "a live broker run WILL produce G2 under concurrency" claim
(``checkers/elle.py:455-458``) into numbers (VERDICT r3 #6's sanctioned
alternative to a broker-side serializable mode, which the architecture
precludes: txn reads ride a dedicated non-tx connection the broker
cannot associate with any transaction scope, so no broker-local lock
can order them into the global tx order).

Each trial runs the real live assembly (``test --db local --workload
elle`` — broker OS process, native C++ tx clients over TCP), then
re-checks the SAME history at both levels:

- read-committed (the contractual level): expected VALID, G2 reported;
- serializable: the same G2 cycles now invalidate.

Writes ``ELLE_G2.md`` at the repo root.

Usage: python tools/measure_g2.py [--trials N] [--time-limit S] [--rate R]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _run(cmd, **kw):
    env = dict(os.environ, JEPSEN_TPU_BACKEND_DEADLINE="15")
    return subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", *cmd],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        **kw,
    )


def one_trial(i: int, time_limit: float, rate: float) -> dict:
    store = tempfile.mkdtemp(prefix=f"g2trial{i}-")
    r = _run(
        [
            "test", "--db", "local", "--workload", "elle",
            "--time-limit", str(time_limit), "--rate", str(rate),
            "--time-before-partition", "999",  # no partition: G2 needs
            "--concurrency", "5",              # only concurrency
            "--seed", str(1000 + i),           # distinct txn programs
            "--checker", "cpu", "--store", store,
        ]
    )
    run_dir = os.path.join(store, "latest")
    results = json.load(open(os.path.join(run_dir, "results.json")))
    elle_rc = results["elle"]
    # the same history, re-checked at serializable
    r2 = _run(
        [
            "check", "--checker", "cpu",
            "--consistency-model", "serializable", run_dir,
        ]
    )
    ser = json.JSONDecoder().raw_decode(
        r2.stdout[r2.stdout.index("{"):]
    )[0]
    elle_ser = ser.get("elle", ser)
    return {
        "trial": i,
        "txns": elle_rc.get("txn-count", 0),
        "rc_valid": elle_rc["valid?"],
        "g2_count": elle_rc.get("G2-count", 0),
        "ser_valid": elle_ser["valid?"],
        "ser_g2_count": elle_ser.get("G2-count", 0),
        "suite_rc": r.returncode,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--trials", type=int, default=10)
    p.add_argument("--time-limit", type=float, default=6.0)
    p.add_argument("--rate", type=float, default=120.0)
    args = p.parse_args()

    rows = []
    for i in range(args.trials):
        t0 = time.time()
        try:
            row = one_trial(i, args.time_limit, args.rate)
        except Exception as e:  # noqa: BLE001 - one bad trial must not
            row = {  # discard the completed ones
                "trial": i, "txns": 0, "rc_valid": None, "g2_count": 0,
                "ser_valid": None, "ser_g2_count": 0, "suite_rc": -1,
                "error": f"{type(e).__name__}: {e}",
            }
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(json.dumps(row), flush=True)

    total_txn = sum(r["txns"] for r in rows)
    total_g2 = sum(r["g2_count"] for r in rows)
    with_g2 = sum(1 for r in rows if r["g2_count"])
    ser_invalid = sum(1 for r in rows if not r["ser_valid"])
    rc_valid = sum(1 for r in rows if r["rc_valid"])

    lines = [
        "# Measured G2 rate of the live elle workload",
        "",
        "The live AMQP-tx mapping's contractual isolation is read",
        "committed (atomic commit visibility; txn reads ride a dedicated",
        "non-tx connection — `native/amqp_driver.cpp:1290-1297`).  The",
        "elle checker checks that level and *reports* G2 anti-dependency",
        "cycles without invalidating (`checkers/elle.py`).  This artifact",
        "gives that claim numbers (VERDICT r3 #6); regenerate with",
        f"`python tools/measure_g2.py --trials {args.trials}`.",
        "",
        f"Config: {args.trials} trials x `test --db local --workload elle "
        f"--time-limit {args.time_limit} --rate {args.rate} "
        f"--concurrency 5` (single broker node, no nemesis — G2 arises "
        "from client concurrency alone), each history re-checked at "
        "serializable.",
        "",
        "| trial | txns | G2 cycles | read-committed | serializable |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        if r.get("error"):
            lines.append(
                f"| {r['trial']} | — | — | trial failed: {r['error']} | — |"
            )
            continue
        lines.append(
            f"| {r['trial']} | {r['txns']} | {r['g2_count']} | "
            f"{'valid' if r['rc_valid'] else 'INVALID'} | "
            f"{'valid' if r['ser_valid'] else 'invalid (G2)'} |"
        )
    pct = 100.0 * with_g2 / len(rows) if rows else 0.0
    lines += [
        "",
        f"**Totals:** {total_txn} txns across {len(rows)} trials; "
        f"{total_g2} G2 cycles; {with_g2}/{len(rows)} trials "
        f"({pct:.0f}%) produced at least one G2; every trial valid at "
        f"read-committed ({rc_valid}/{len(rows)}); {ser_invalid} trials "
        "invalidated when re-checked at serializable.",
        "",
        "Reading: G2 here is *genuine SUT behavior under its contract*, "
        "not a checker gap — the same histories flip to invalid the "
        "moment the claimed level is tightened to serializable "
        "(`check --consistency-model serializable`).",
        "",
    ]
    out = os.path.join(REPO, "ELLE_G2.md")
    with open(out, "w") as fh:
        fh.write("\n".join(lines))
    print(f"wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
