#!/usr/bin/env python
"""Store CAS accounting and garbage collection (COLUMNAR.md
§Content-addressed sections).

Default is read-only: report the store's honest dedup ratio — logical
bytes addressed by every ``.casman.json`` manifest under the store
tree vs unique content-addressed object bytes on disk (1.0 means
nothing is shared; the tool never inflates).  ``--collect`` removes
UNREFERENCED objects only (hardlink count 1); a referenced object is
live manifest data and is refused loudly even under ``--force`` — the
flag exists so the refusal is observable, not so it can be overridden.

    python tools/store_gc.py store/             # dedup report (JSON)
    python tools/store_gc.py store/ --collect   # drop unreferenced
    python tools/store_gc.py store/ --verify    # re-hash every object
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from jepsen_tpu.history.cas import (  # noqa: E402
    DEFAULT_CAS_DIR,
    SectionStore,
    dedup_stats,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("root", help="store tree holding manifests + cas/")
    ap.add_argument(
        "--cas", default=None,
        help=f"CAS directory (default: <root>/{DEFAULT_CAS_DIR})",
    )
    ap.add_argument(
        "--collect", action="store_true",
        help="remove unreferenced objects (nlink == 1)",
    )
    ap.add_argument(
        "--force", action="store_true",
        help="does NOT collect referenced objects — it makes each "
             "refusal explicit in the report",
    )
    ap.add_argument(
        "--verify", action="store_true",
        help="re-hash every object and report corruption",
    )
    args = ap.parse_args(argv)

    cas = SectionStore(
        args.cas if args.cas else os.path.join(args.root, DEFAULT_CAS_DIR)
    )
    out = {"dedup": dedup_stats(args.root, cas)}
    if args.verify:
        bad = []
        for sha, _p, _size, _nlink in cas.iter_objects():
            try:
                cas.get(sha)
            except Exception as e:  # noqa: BLE001 - reported, not fatal
                bad.append({"sha": sha, "error": str(e)})
        out["verify"] = {"corrupt": bad, "ok": not bad}
    if args.collect:
        out["gc"] = cas.gc(force=args.force)
    print(json.dumps(out, indent=2))
    if args.verify and out["verify"]["corrupt"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
