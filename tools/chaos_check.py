#!/usr/bin/env python
"""Checker-chaos harness: a nemesis pointed at the CHECKER itself
(ROADMAP direction 5(d)) — the differential proof behind PR 13's
elastic resilience.

Builds a synthetic corpus (optionally laced with poison histories —
torn-JSON files that crash the packer), launches the elastic
multi-process checker (``parallel/distributed.py``), and mid-check
SIGKILLs / SIGSTOPs ``--kill`` of the ``--procs`` workers (or uses the
deterministic die-after-claim env hook).  Then proves, fail-loud:

- every NON-quarantined history's verdict is IDENTICAL to the serial
  oracle computed before the chaos;
- every poison history reports ``unknown`` with the captured exception
  as evidence (never a silent drop, never a fabricated verdict);
- the ``degraded`` provenance is accurate: the dead/wedged workers are
  named, their stripes' requeues recorded, quarantines counted.

Artifacts land in ``--out`` (e.g. ``store/chaos_r13``): a capture log
(``chaos_check.log``) and a machine-readable ``results.json`` carrying
the config, the degraded provenance, and the verdict summary.  Exit 0
only if every assertion held.

Examples:
  python tools/chaos_check.py --procs 3 --kill 1 --mode sigkill \
      --histories 200 --ops 100 --poison 2 --out store/chaos_smoke
  python tools/chaos_check.py --procs 3 --kill 2 --mode sigkill \
      --histories 10000 --ops 1000 --oracle pipeline --out store/chaos_ns
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
# --serve reuses the bench_serve arms; resolvable even when this file
# is loaded as a module rather than run as a script from tools/
sys.path.insert(0, str(Path(__file__).resolve().parent))

POISON_LINE = '{"type": "not a real op"\n'  # torn JSON: crashes the parse


class _Log:
    def __init__(self, path: Path | None):
        self.path = path
        self.lines: list[str] = []
        if path is not None:
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text("")

    def __call__(self, msg: str) -> None:
        line = f"[{time.strftime('%H:%M:%S')}] {msg}"
        self.lines.append(line)
        print(line, flush=True)
        if self.path is not None:
            with open(self.path, "a") as fh:
                fh.write(line + "\n")


def _build_corpus(corpus_dir: Path, args, log) -> tuple[list[str], set]:
    """Synthesize ``--base`` real history files, replicate their paths
    to ``--histories`` sources, and splice ``--poison`` torn-JSON files
    at spread positions.  Returns (sources, poison_positions)."""
    from jepsen_tpu.history.store import write_history_jsonl
    from jepsen_tpu.history.synth import (
        StreamSynthSpec, SynthSpec, synth_batch, synth_stream_batch,
    )

    corpus_dir.mkdir(parents=True, exist_ok=True)
    if args.workload == "stream":
        base = synth_stream_batch(
            args.base, StreamSynthSpec(n_ops=args.ops, seed=args.seed),
            lost=1, duplicated=1,
        )
    else:
        base = synth_batch(
            args.base, SynthSpec(n_ops=args.ops, seed=args.seed),
            lost=1, duplicated=1,
        )
    files = []
    for i, sh in enumerate(base):
        p = corpus_dir / f"h{i:04d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(str(p))
    srcs = (files * ((args.histories + args.base - 1) // args.base))[
        : args.histories
    ]
    poison_pos: set = set()
    if args.poison:
        step = max(1, len(srcs) // (args.poison + 1))
        for j in range(args.poison):
            p = corpus_dir / f"poison{j:02d}.jsonl"
            p.write_text(POISON_LINE)
            pos = min((j + 1) * step, len(srcs))
            srcs.insert(pos, str(p))
            # earlier inserts shift later positions by construction:
            # insert left-to-right and account for the offset
        # recompute positions after all inserts
        poison_pos = {
            i for i, s in enumerate(srcs) if "poison" in Path(s).name
        }
    log(
        f"corpus: {len(srcs)} sources ({args.base} real files x "
        f"{args.ops} ops, {len(poison_pos)} poison) under {corpus_dir}"
    )
    return srcs, poison_pos


def _oracle(args, srcs, poison_pos, log):
    """Pre-chaos verdicts for every non-poison source.  ``--oracle
    serial`` is the strict single-thread serial executor;
    ``--oracle pipeline`` is the in-process fail-fast lanes executor
    (differentially pinned ≡ serial in tests/test_pipeline.py — the
    honest shortcut for north-star-sized corpora)."""
    from jepsen_tpu.parallel.pipeline import check_sources

    good = [s for i, s in enumerate(srcs) if i not in poison_pos]
    t0 = time.perf_counter()
    if args.oracle == "serial":
        results, _ = check_sources(
            args.workload, good, chunk=args.chunk, serial=True,
        )
    else:
        results, _ = check_sources(
            args.workload, good, chunk=args.chunk, lanes=0,
            fail_fast=True,
        )
    log(
        f"oracle ({args.oracle}): {len(good)} histories in "
        f"{time.perf_counter() - t0:.1f}s"
    )
    out: dict[int, dict] = {}
    j = 0
    for i in range(len(srcs)):
        if i in poison_pos:
            continue
        out[i] = results[j]
        j += 1
    return out


def _nemesis_hook(args, log, state):
    """The checker-nemesis: ``--kill`` workers get SIGKILL/SIGSTOP
    ``--kill-after`` seconds after spawn — mid-check by construction on
    any non-trivial corpus."""
    if args.mode == "die-env" or args.kill == 0:
        return None

    sig = signal.SIGKILL if args.mode == "sigkill" else signal.SIGSTOP

    def hook(procs):
        def nemesis():
            time.sleep(args.kill_after)
            victims = [p for p in range(1, len(procs))][: args.kill]
            for pid in victims:
                if procs[pid].poll() is None:
                    log(
                        f"nemesis: {args.mode.upper()} worker {pid} "
                        f"(os pid {procs[pid].pid}) at "
                        f"t+{args.kill_after:.1f}s"
                    )
                    try:
                        procs[pid].send_signal(sig)
                        state["signalled"].append(pid)
                    except OSError as e:
                        log(f"nemesis: signal failed for {pid}: {e}")

        threading.Thread(target=nemesis, daemon=True).start()

    return hook


def _seg_child_cmd(hist, seg_ops, resume=False, die_after=None):
    """One segmented check in a subprocess (the crashable unit)."""
    code = (
        "import sys, json; sys.path.insert(0, sys.argv[1])\n"
        "from jepsen_tpu.checkers.segmented import segmented_check_file\n"
        "from jepsen_tpu.history.store import _json_default\n"
        "r = segmented_check_file(sys.argv[2], workload='queue',"
        " segment_ops=int(sys.argv[3]), device=False,"
        f" resume={bool(resume)})\n"
        "print('SEG_RESULT ' + json.dumps(r, default=_json_default),"
        " flush=True)\n"
    )
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    if die_after is not None:
        env["JEPSEN_TPU_SEG_DIE_AFTER"] = str(die_after)
    else:
        env.pop("JEPSEN_TPU_SEG_DIE_AFTER", None)
    return (
        [sys.executable, "-c", code, str(REPO), str(hist), str(seg_ops)],
        env,
    )


def _run_seg_child(hist, seg_ops, log, resume=False, die_after=None,
                   kill_after=None, timeout=600.0):
    import subprocess

    argv, env = _seg_child_cmd(hist, seg_ops, resume, die_after)
    p = subprocess.Popen(
        argv, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    if kill_after is not None:

        def _killer():
            time.sleep(kill_after)
            if p.poll() is None:
                log(f"nemesis: SIGKILL segmented checker (pid {p.pid}) "
                    f"at t+{kill_after:.2f}s")
                p.kill()

        threading.Thread(target=_killer, daemon=True).start()
    out, err = p.communicate(timeout=timeout)
    result = None
    for line in out.splitlines():
        if line.startswith("SEG_RESULT "):
            result = json.loads(line[len("SEG_RESULT "):])
    return p.returncode, result, err


def run_segmented_chaos(args, log, check) -> None:
    """Kill-mid-segment / resume proofs for the SEGMENTED checker
    (ISSUE 15): an uninterrupted oracle run, a mid-check death (real
    SIGKILL or the deterministic die-after-segment env hook), a
    resume that must reach the IDENTICAL verdict from the last
    checkpoint, and a torn-checkpoint refusal that recomputes from
    the previous one — all fail-loud."""
    from jepsen_tpu.checkers.segmented import checkpoint_path_for
    from jepsen_tpu.history.store import write_history_jsonl
    from jepsen_tpu.history.synth import SynthSpec, synth_history

    corpus = Path(args.corpus_dir or tempfile.mkdtemp(prefix="jt_segchaos_"))
    corpus.mkdir(parents=True, exist_ok=True)
    hist = corpus / "history.jsonl"
    sh = synth_history(
        SynthSpec(n_ops=args.seg_history_ops, seed=args.seed,
                  lost=1, duplicated=1)
    )
    write_history_jsonl(hist, sh.ops)
    n_lines = sum(1 for _ in open(hist))
    seg_ops = args.seg_ops
    log(
        f"segmented chaos: {n_lines} op lines, segment_ops={seg_ops} "
        f"(~{n_lines // seg_ops} segments), mode={args.mode}"
    )
    ckpt = checkpoint_path_for(hist)

    # 1. uninterrupted oracle
    t_oracle = time.perf_counter()
    rc, oracle, err = _run_seg_child(hist, seg_ops, log)
    oracle_wall = time.perf_counter() - t_oracle
    check(rc == 0 and oracle is not None,
          f"uninterrupted segmented run completed (rc={rc})")
    check(not ckpt.exists(),
          "a COMPLETED run leaves no checkpoint behind")
    check(oracle["segmented"]["resumed"] is False,
          "an uninterrupted run never claims a resume")

    # 2. kill mid-check
    die_after = None
    kill_after = None
    if args.mode == "die-env":
        die_after = max(1, (n_lines // seg_ops) // 2)
    else:
        # the kill must land MID-check: a fixed delay races a fast
        # host (the r13 chaos-smoke lesson), so cap it at ~40% of the
        # measured uninterrupted wall
        kill_after = min(args.kill_after, max(0.2, 0.4 * oracle_wall))
        if kill_after < args.kill_after:
            log(
                f"nemesis: --kill-after {args.kill_after:.1f}s would "
                f"outlive the {oracle_wall:.1f}s check — scaled to "
                f"{kill_after:.2f}s"
            )
    rc, res, err = _run_seg_child(
        hist, seg_ops, log, die_after=die_after, kill_after=kill_after
    )
    check(rc != 0 and res is None,
          f"mid-check death produced no verdict (rc={rc})")
    check(ckpt.exists(), "the killed run left a durable checkpoint")

    # 3. resume -> identical verdict
    rc, resumed, err = _run_seg_child(hist, seg_ops, log, resume=True)
    check(rc == 0 and resumed is not None,
          f"resumed run completed (rc={rc})")
    meta = (resumed or {}).get("segmented", {})
    check(bool(meta.get("resumed")) and meta.get("resumed_from", -1) >= 0,
          f"resume came from a checkpoint "
          f"(resumed_from={meta.get('resumed_from')})")
    same = all(
        (resumed or {}).get(k) == oracle.get(k)
        for k in ("queue", "linear", "valid?")
    )
    check(same, "resumed verdict IDENTICAL to the uninterrupted run")

    # 4. torn checkpoint: refused loudly, recomputed from the previous
    rc, _res, err = _run_seg_child(
        hist, seg_ops, log, die_after=die_after, kill_after=kill_after
    )
    check(ckpt.exists(), "second killed run left a checkpoint to tear")
    raw = ckpt.read_bytes()
    ckpt.write_bytes(raw[: len(raw) // 2])  # torn mid-write
    rc, resumed2, err = _run_seg_child(hist, seg_ops, log, resume=True)
    meta2 = (resumed2 or {}).get("segmented", {})
    check(
        rc == 0 and bool(meta2.get("checkpoints_refused")),
        f"torn checkpoint REFUSED loudly "
        f"(refusals={meta2.get('checkpoints_refused')})",
    )
    same2 = all(
        (resumed2 or {}).get(k) == oracle.get(k)
        for k in ("queue", "linear", "valid?")
    )
    check(same2,
          "torn-checkpoint recovery still reaches the identical verdict")


def run_serve_chaos(args, log, check) -> dict:
    """ISSUE-16 mode: the nemesis pointed at the always-on streaming
    SERVICE — a zero-kill honesty row, the die-hook killing checker
    worker 0 mid-feed under concurrent streams (surviving verdicts ≡
    the serial oracle, degraded provenance names the corpse), and a
    saturation burst whose books must balance exactly (loud SATURATED,
    zero silent drops, zero gapped carries).  Reuses the arms of
    tools/bench_serve.py so the chaos artifact and the bench measure
    the same code paths."""
    import bench_serve

    ns = argparse.Namespace(
        histories=0, base=8, ops=args.ops, workers=args.procs,
        seed=args.seed, min_rate=0.0, cache_ops=0, cache_reps=0,
        chaos_streams=max(args.histories, 4), chaos_ops=args.serve_ops,
        chaos_blocks=8, kill_block=args.serve_kill_block,
        sat_submits=64, sat_block_delay=0.02, timeout=args.timeout,
        device=False,
    )
    return {
        "chaos": bench_serve.arm_chaos(ns, log, check),
        "saturation": bench_serve.arm_saturation(ns, log, check),
    }


def _run_campaign_child(args, out_dir, log, extra=(), env_extra=None,
                        kill_after=None, timeout=1800.0):
    """One ``python -m jepsen_tpu campaign`` subprocess (the crashable
    unit of the ISSUE-17 mode).  Returns (rc, summary|None, stderr) —
    the campaign CLI prints its summary JSON alone on stdout."""
    import subprocess

    argv = [
        sys.executable, "-m", "jepsen_tpu", "campaign",
        "--out", str(out_dir), "--seed", str(args.seed),
        "--trials", str(args.campaign_trials),
        "--ops", str(args.campaign_ops),
        "--faults", args.campaign_faults,
    ] + list(extra)
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JEPSEN_TPU_CAMPAIGN_DIE_AFTER", None)
    env.pop("JEPSEN_TPU_CAMPAIGN_FORCE_RED", None)
    env.update(env_extra or {})
    p = subprocess.Popen(
        argv, cwd=str(REPO), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    if kill_after is not None:

        def _killer():
            time.sleep(kill_after)
            if p.poll() is None:
                log(f"nemesis: SIGKILL campaign supervisor "
                    f"(pid {p.pid}) at t+{kill_after:.2f}s")
                p.kill()

        threading.Thread(target=_killer, daemon=True).start()
    out, err = p.communicate(timeout=timeout)
    summary = None
    try:
        summary = json.loads(out)
    except ValueError:
        pass
    return p.returncode, summary, err


def run_campaign_chaos(args, log, check) -> dict:
    """ISSUE-17 mode: the nemesis pointed at the CAMPAIGN SUPERVISOR —
    an uninterrupted oracle campaign (every served verdict ≡ the serial
    oracle, books balanced, verdict windows PUSHED, record→verdict
    p50/p99 measured), then a real supervisor SIGKILL mid-campaign (or
    the deterministic die-after-trial env hook) whose ``--resume`` must
    land on the IDENTICAL fingerprint set, and optionally a live-tailed
    soak (``--campaign-live``) closing record→stream→verdict with no
    recorded file in between.  The campaign itself already contains the
    service-SIGKILL+restart and torn-subscription arms."""
    from jepsen_tpu.campaign.ledger import read_ledger

    out_root = Path(args.corpus_dir)
    faults = [f for f in args.campaign_faults.split(",") if f.strip()]

    # 1. the uninterrupted oracle campaign
    t0 = time.perf_counter()
    rc, oracle, err = _run_campaign_child(
        args, out_root / "oracle", log, timeout=args.timeout
    )
    oracle_wall = time.perf_counter() - t0
    check(rc == 0 and oracle is not None,
          f"uninterrupted campaign completed green (rc={rc})")
    if oracle is None:
        log(f"campaign stderr tail:\n{err[-2000:]}")
        return {}
    check(oracle["completed"] == oracle["planned"],
          f"all {oracle['planned']} planned trials completed")
    check(oracle["reds"] == 0, "zero unexpected reds")
    check(
        oracle["oracle_matches"] == oracle["completed"],
        f"every served verdict ≡ post-hoc serial oracle "
        f"({oracle['oracle_matches']}/{oracle['completed']})",
    )
    check(bool(oracle["books_balanced"]),
          "books balance exactly on every trial "
          "(submitted == verdicts + rejects + interrupted)")
    check(
        oracle["windows_pushed"] >= oracle["completed"],
        f"verdict windows PUSHED before stream finish "
        f"({oracle['windows_pushed']} across "
        f"{oracle['completed']} trials)",
    )
    check(
        set(faults) <= set(oracle["faults_fired"]),
        f"every enabled fault fired: {oracle['faults_fired']}",
    )
    p50 = oracle["record_to_verdict_ms"]["p50"]
    p99 = oracle["record_to_verdict_ms"]["p99"]
    check(p50 is not None and p99 is not None,
          f"record-to-verdict latency measured: "
          f"p50={p50}ms p99={p99}ms")
    odoc = read_ledger(out_root / "oracle" / "campaign_ledger.json")
    ofps = [t["fingerprint"] for t in odoc["trials"]]
    if "service-restart" in faults:
        restarted = [t for t in odoc["trials"]
                     if t["spec"]["fault"] == "service-restart"]
        check(
            bool(restarted) and all(
                t.get("restarted") and t["books"]["interrupted"] >= 1
                for t in restarted
            ),
            f"service-restart arm: {len(restarted)} real service "
            f"SIGKILL+restart(s), interrupted stream accounted in "
            f"books",
        )
    if "torn-subscription" in faults:
        torn = [t for t in odoc["trials"]
                if t["spec"]["fault"] == "torn-subscription"]
        check(
            bool(torn) and all(
                t["subscriber_error"] is None
                and t["windows_pushed"] > 0
                for t in torn
            ),
            "torn-subscription arm: subscriber reconnected and "
            "replayed the missed windows (no residual error, windows "
            "complete)",
        )

    # 2. kill the supervisor MID-campaign
    chaos_out = out_root / "chaos"
    if args.mode == "die-env":
        die_n = max(0, args.campaign_trials // 2 - 1)
        log(f"nemesis: die-after-trial hook armed at trial {die_n}")
        rc, _s, err = _run_campaign_child(
            args, chaos_out, log,
            env_extra={"JEPSEN_TPU_CAMPAIGN_DIE_AFTER": str(die_n)},
            timeout=args.timeout,
        )
        check(rc == 137,
              f"die-hook supervisor exited 137 mid-campaign (rc={rc})")
    else:
        kill_after = max(args.kill_after, 0.45 * oracle_wall)
        if kill_after > args.kill_after:
            log(f"nemesis: --kill-after {args.kill_after:.1f}s would "
                f"land before the first journaled trial — scaled to "
                f"{kill_after:.1f}s (45% of the {oracle_wall:.1f}s "
                f"oracle wall)")
        rc, _s, err = _run_campaign_child(
            args, chaos_out, log, kill_after=kill_after,
            timeout=args.timeout,
        )
        check(rc != 0, f"SIGKILLed supervisor died loudly (rc={rc})")
    ledger_path = chaos_out / "campaign_ledger.json"
    check(ledger_path.exists(),
          "the killed supervisor left a durable ledger behind")
    journaled = (
        len(read_ledger(ledger_path)["trials"])
        if ledger_path.exists() else 0
    )
    check(
        0 < journaled < args.campaign_trials,
        f"the kill landed MID-campaign "
        f"({journaled}/{args.campaign_trials} trials journaled)",
    )

    # 3. resume: the journaled prefix is skipped, the verdict set is
    # IDENTICAL to the uninterrupted run's
    rc, resumed, err = _run_campaign_child(
        args, chaos_out, log, extra=["--resume"], timeout=args.timeout
    )
    check(rc == 0 and resumed is not None,
          f"resumed campaign completed green (rc={rc})")
    if resumed is not None:
        check(
            resumed["resumed_from"] == journaled,
            f"resume skipped exactly the journaled prefix "
            f"({resumed['resumed_from']} == {journaled})",
        )
        check(resumed["completed"] == resumed["planned"]
              and resumed["reds"] == 0
              and bool(resumed["books_balanced"]),
              "resumed campaign: all trials green, books balanced")
    rfps = [t["fingerprint"]
            for t in read_ledger(ledger_path)["trials"]]
    check(
        rfps == ofps,
        f"kill→resume verdict fingerprints IDENTICAL to the "
        f"uninterrupted campaign ({len(rfps)} trials)",
    )

    result = {
        "oracle": oracle,
        "oracle_wall_s": round(oracle_wall, 2),
        "journaled_at_kill": journaled,
        "resumed": resumed,
        "fingerprints": rfps,
    }

    # 4. optional: the live-tailing leg — a soak whose op blocks go
    # STRAIGHT into a real service subprocess, verdict formed on the
    # live stream (tools/soak.py --live-stream, campaign tentpole (a))
    if args.campaign_live:
        import subprocess

        from jepsen_tpu.campaign.supervisor import (
            _free_port, _spawn_service,
        )

        port = _free_port()
        svc = _spawn_service(port, str(out_root / "live_store"))
        try:
            log(f"live-tail: soak --live-stream 127.0.0.1:{port} "
                f"({args.campaign_live_minutes} min)")
            p = subprocess.run(
                [sys.executable, "tools/soak.py", "--workload",
                 "queue", "--minutes",
                 str(args.campaign_live_minutes),
                 "--live-stream", f"127.0.0.1:{port}"],
                cwd=str(REPO),
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
                capture_output=True, text=True,
                timeout=args.timeout,
            )
            tail_lines = [
                ln for ln in p.stdout.splitlines()
                if "PUSHED" in ln or "record-to-verdict" in ln
            ]
            for ln in tail_lines:
                log(f"live-tail: {ln.strip()}")
            check(
                p.returncode == 0 and any(
                    "PUSHED" in ln for ln in tail_lines
                ),
                f"live-tailed soak green with pushed verdict windows "
                f"(rc={p.returncode})",
            )
            result["live_tail"] = {
                "rc": p.returncode,
                "summary_lines": [ln.strip() for ln in tail_lines],
            }
        finally:
            svc.kill()
            svc.wait(timeout=30)

    return {"campaign": result}


def run_global_mesh_chaos(args, log, check) -> dict:
    """ISSUE-18 mode: the nemesis pointed at the GLOBAL-MESH fleet —
    N processes joined into ONE ``jax.distributed`` mesh running the
    collective verdict program, a worker SIGKILLed (or wedged, or the
    deterministic die-between-stripes hook) MID-CLOSURE.  A dead member
    wedges the survivors inside collectives, so the proof is the
    generation story: the launcher kills the generation, respawns N-1
    on a fresh coordinator, skips ledgered stripes, and the final
    reduced verdict must equal the elastic single-process oracle (an
    independent execution path: per-process mesh, no cross-host
    collectives) — or quarantine loudly, never fabricate."""
    from jepsen_tpu.history.store import write_history_jsonl
    from jepsen_tpu.history.synth import (
        ElleSynthSpec, SynthSpec, synth_batch, synth_elle_batch,
    )
    from jepsen_tpu.parallel.distributed import (
        degraded_active, run_multiprocess_check,
    )

    corpus = Path(args.corpus_dir)
    corpus.mkdir(parents=True, exist_ok=True)
    if args.workload == "elle":
        base = synth_elle_batch(
            max(1, args.base - 2),
            ElleSynthSpec(n_txns=args.ops, seed=args.seed), g2_cycle=1,
        ) + synth_elle_batch(
            2, ElleSynthSpec(n_txns=args.ops, seed=args.seed + 1)
        )
    else:
        base = synth_batch(
            args.base, SynthSpec(n_ops=args.ops, seed=args.seed),
            lost=1, duplicated=1,
        )
    files = []
    for i, sh in enumerate(base):
        p = corpus / f"h{i:04d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(str(p))
    srcs = (files * ((args.histories + len(files) - 1) // len(files)))[
        : args.histories
    ]
    unit = "txns" if args.workload == "elle" else "ops"
    log(
        f"global-mesh corpus: {len(srcs)} sources ({len(files)} real "
        f"files x {args.ops} {unit}), workload={args.workload} "
        f"seq={args.gm_seq}"
    )

    def vkeys(v):
        return {k: v[k] for k in ("histories", "invalid", "first_invalid")}

    # 1. the oracle: the ELASTIC single-process meshed reduction — an
    # independent execution path (per-process mesh, no cross-host
    # collectives) already differentially pinned to serial in tests
    t0 = time.perf_counter()
    oracle, _oinfo = run_multiprocess_check(
        args.workload, srcs, 1, devices_per_proc=args.devices_per_proc,
        chunk=args.chunk, mesh=True, reduce=True, timeout_s=args.timeout,
    )
    log(
        f"oracle (elastic 1-proc reduced): {vkeys(oracle)} in "
        f"{time.perf_counter() - t0:.1f}s"
    )

    # 2. the no-kill honesty row: the global mesh must agree BEFORE any
    # chaos and report a clean provenance
    t0 = time.perf_counter()
    clean, cinfo = run_multiprocess_check(
        args.workload, srcs, args.procs,
        devices_per_proc=args.devices_per_proc, chunk=args.chunk,
        reduce=True, global_mesh=True, seq=args.gm_seq,
        timeout_s=args.timeout,
    )
    nokill_wall = time.perf_counter() - t0
    log(
        f"no-kill global mesh ({args.procs} procs): {vkeys(clean)} in "
        f"{nokill_wall:.1f}s"
    )
    check(
        vkeys(clean) == vkeys(oracle),
        f"no-kill global-mesh verdict == elastic oracle ({vkeys(clean)})",
    )
    check(
        not degraded_active(cinfo["degraded"]),
        "no-kill run reports a clean degraded provenance",
    )

    # 3. kill --kill of --procs mid-closure (first generation only —
    # the respawned generation must be left alone to finish)
    state: dict = {"signalled": []}
    hook = None
    if args.mode == "die-env":
        os.environ["JEPSEN_TPU_DIST_DIE_PID"] = ",".join(
            str(q) for q in range(1, 1 + args.kill)
        )
        log(
            "nemesis: die-between-stripes hook armed for pid(s) "
            f"{os.environ['JEPSEN_TPU_DIST_DIE_PID']}"
        )
    else:
        sig = signal.SIGKILL if args.mode == "sigkill" else signal.SIGSTOP
        kill_after = min(args.kill_after, max(0.3, 0.45 * nokill_wall))
        if kill_after < args.kill_after:
            log(
                f"nemesis: --kill-after {args.kill_after:.1f}s would "
                f"outlive the {nokill_wall:.1f}s run — scaled to "
                f"{kill_after:.2f}s"
            )
        fired = {"done": False}

        def hook(procs):
            if fired["done"]:
                return
            fired["done"] = True

            def nemesis():
                time.sleep(kill_after)
                for pid in range(1, 1 + args.kill):
                    if pid < len(procs) and procs[pid].poll() is None:
                        log(
                            f"nemesis: {args.mode.upper()} worker {pid} "
                            f"(os pid {procs[pid].pid}) at "
                            f"t+{kill_after:.2f}s — mid-closure"
                        )
                        try:
                            procs[pid].send_signal(sig)
                            state["signalled"].append(pid)
                        except OSError as e:
                            log(f"nemesis: signal failed for {pid}: {e}")

            threading.Thread(target=nemesis, daemon=True).start()

    t0 = time.perf_counter()
    try:
        results, info = run_multiprocess_check(
            args.workload, srcs, args.procs,
            devices_per_proc=args.devices_per_proc, chunk=args.chunk,
            reduce=True, global_mesh=True, seq=args.gm_seq,
            timeout_s=args.timeout,
            stripe_timeout_s=(
                args.stripe_timeout if args.mode == "sigstop" else None
            ),
            _proc_hook=hook,
        )
    finally:
        os.environ.pop("JEPSEN_TPU_DIST_DIE_PID", None)
    wall = time.perf_counter() - t0
    deg = info["degraded"]
    log(
        f"chaos global mesh: {vkeys(results)} in {wall:.1f}s; "
        f"degraded={deg}"
    )

    if args.mode == "sigstop":
        check(
            deg["wedged_killed"] >= 1,
            f"wedged generation killed by the stripe deadline "
            f"(wedged_killed={deg['wedged_killed']})",
        )
    else:
        check(
            len(deg["dead_workers"]) >= 1,
            f"provenance names the dead worker(s): {deg['dead_workers']}",
        )
        check(
            deg["final_procs"] < args.procs,
            f"fleet shrank after the death "
            f"(final_procs={deg['final_procs']})",
        )
    check(
        deg["generations"] >= 2,
        f"the death forced a generation respawn "
        f"(generations={deg['generations']})",
    )
    check(
        results["histories"] + deg["quarantined_histories"]
        == oracle["histories"],
        "every history accounted for: verdict + quarantined == corpus",
    )
    if deg["quarantined_histories"] == 0:
        check(
            vkeys(results) == vkeys(oracle),
            f"post-chaos verdict == elastic oracle ({vkeys(results)})",
        )
    else:
        log(
            f"note: {deg['quarantined_histories']} histories "
            f"quarantined after retries — verdict covers the remainder"
        )
    return {
        "oracle": vkeys(oracle),
        "nokill": {
            "verdict": vkeys(clean),
            "wall_s": round(nokill_wall, 2),
        },
        "chaos": {
            "verdict": vkeys(results),
            "wall_s": round(wall, 2),
            "degraded": deg,
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
    )
    p.add_argument("--procs", type=int, default=3)
    p.add_argument("--kill", type=int, default=1,
                   help="workers to kill/stop mid-check (< --procs)")
    p.add_argument(
        "--mode", choices=("sigkill", "sigstop", "die-env"),
        default="sigkill",
        help="sigkill: hard death mid-check; sigstop: wedge (the "
        "per-stripe deadline must fire); die-env: deterministic "
        "die-after-claim hook (CI)",
    )
    p.add_argument("--kill-after", type=float, default=3.0)
    p.add_argument("--histories", type=int, default=48)
    p.add_argument("--base", type=int, default=16,
                   help="distinct synthesized history files")
    p.add_argument("--ops", type=int, default=60)
    p.add_argument("--workload", choices=("stream", "queue", "elle"),
                   default="stream")
    p.add_argument("--poison", type=int, default=0,
                   help="torn-JSON poison histories spliced mid-corpus")
    p.add_argument("--chunk", type=int, default=64)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--devices-per-proc", type=int, default=1)
    p.add_argument("--stripe-timeout", type=float, default=15.0,
                   help="per-stripe deadline (the SIGSTOP recovery path)")
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--oracle", choices=("serial", "pipeline"),
                   default="serial")
    p.add_argument("--out", default=None,
                   help="artifact dir (e.g. store/chaos_r13)")
    p.add_argument("--corpus-dir", default=None,
                   help="keep the synthesized corpus here (default: a "
                   "temp dir — the corpus is reproducible from the "
                   "seed and never belongs beside committed artifacts)")
    p.add_argument("--segmented", action="store_true",
                   help="ISSUE-15 mode: chaos against the SEGMENTED "
                   "checker instead of the worker fleet — one long "
                   "history, SIGKILL (or die-after-segment hook) "
                   "mid-check, resume from the last checkpoint, "
                   "torn-checkpoint refusal; proves the resumed "
                   "verdict is identical to the uninterrupted run")
    p.add_argument("--seg-ops", type=int, default=500,
                   help="--segmented: ops per segment")
    p.add_argument("--seg-history-ops", type=int, default=4000,
                   help="--segmented: synthesized history op "
                   "invocations (the file is ~2x lines)")
    p.add_argument("--serve", action="store_true",
                   help="ISSUE-16 mode: chaos against the always-on "
                   "streaming SERVICE (service/stream.py) — a "
                   "zero-kill honesty row, a checker-worker death "
                   "mid-feed under concurrent streams, and a "
                   "saturation burst with exact loud-reject "
                   "accounting; --procs is the worker pool size")
    p.add_argument("--serve-ops", type=int, default=1200,
                   help="--serve: ops per streamed history")
    p.add_argument("--serve-kill-block", type=int, default=3,
                   help="--serve: worker 0 dies mid-feed of its Nth "
                   "block")
    p.add_argument("--campaign", action="store_true",
                   help="ISSUE-17 mode: chaos against the CAMPAIGN "
                   "SUPERVISOR (campaign/supervisor.py) — an "
                   "uninterrupted oracle campaign (which itself "
                   "contains the service-SIGKILL+restart and "
                   "torn-subscription arms), a real supervisor "
                   "SIGKILL mid-campaign (or the die-after-trial "
                   "hook under --mode die-env), and a --resume that "
                   "must land on the identical fingerprint set")
    p.add_argument("--campaign-trials", type=int, default=6,
                   help="--campaign: trials per campaign run")
    p.add_argument("--campaign-ops", type=int, default=160,
                   help="--campaign: ops per corpus history")
    p.add_argument("--campaign-faults",
                   default="none,kill-worker,service-restart,"
                   "torn-subscription",
                   help="--campaign: fault vocabulary (comma list)")
    p.add_argument("--campaign-live", action="store_true",
                   help="--campaign: add the live-tailing leg — a "
                   "soak whose op blocks stream STRAIGHT into a real "
                   "service subprocess (tools/soak.py --live-stream)")
    p.add_argument("--campaign-live-minutes", type=float, default=0.2,
                   help="--campaign-live: soak duration in minutes")
    p.add_argument("--global-mesh", action="store_true",
                   help="ISSUE-18 mode: chaos against the GLOBAL-MESH "
                   "fleet — N processes joined into one "
                   "jax.distributed mesh running the collective "
                   "verdict program, --kill of them SIGKILLed "
                   "mid-closure (or wedged under --mode sigstop, or "
                   "the deterministic die-between-stripes hook under "
                   "--mode die-env); proves the generation respawn "
                   "reaches the elastic oracle's verdict")
    p.add_argument("--gm-seq", type=int, default=1,
                   help="--global-mesh: sequence-axis width of the "
                   "global mesh (must divide into --procs x "
                   "--devices-per-proc; seq>1 shards the packed "
                   "closure's plane axis across hosts)")
    args = p.parse_args(argv)
    if args.workload == "elle" and not args.global_mesh:
        p.error("--workload elle is wired for --global-mesh mode")
    if (not (args.segmented or args.serve or args.campaign)
            and args.kill >= args.procs):
        p.error("--kill must leave at least one survivor (< --procs)")
    if args.segmented and args.mode == "sigstop":
        p.error("--segmented supports sigkill / die-env (a SIGSTOPped "
                "single-process check has no peer to detect the wedge)")
    if args.campaign and args.mode == "sigstop":
        p.error("--campaign supports sigkill / die-env (a SIGSTOPped "
                "supervisor is a hung client, not a crash — the "
                "resume story needs a corpse)")

    out_dir = Path(args.out) if args.out else None
    log = _Log(out_dir / "chaos_check.log" if out_dir else None)
    log(
        f"chaos_check: procs={args.procs} kill={args.kill} "
        f"mode={args.mode} histories={args.histories} ops={args.ops} "
        f"poison={args.poison} workload={args.workload} "
        f"oracle={args.oracle} seed={args.seed}"
    )

    from jepsen_tpu.history.store import _json_default

    if args.campaign:
        failures: list[str] = []

        def ccheck(cond: bool, msg: str) -> None:
            if cond:
                log(f"PASS  {msg}")
            else:
                failures.append(msg)
                log(f"FAIL  {msg}")

        t0 = time.perf_counter()
        tmp_ctx = (
            tempfile.TemporaryDirectory(prefix="jt_campchaos_")
            if args.corpus_dir is None
            else None
        )
        if tmp_ctx is not None:
            args.corpus_dir = tmp_ctx.name
        try:
            arms = run_campaign_chaos(args, log, ccheck)
        finally:
            if tmp_ctx is not None:
                tmp_ctx.cleanup()
        if out_dir is not None:
            doc = {
                "tool": "chaos_check --campaign",
                "pass": not failures,
                "config": {
                    k: v for k, v in vars(args).items() if k != "out"
                },
                "wall_s": round(time.perf_counter() - t0, 2),
                "failures": failures,
                **arms,
            }
            out_dir.mkdir(parents=True, exist_ok=True)
            (out_dir / "results.json").write_text(
                json.dumps(doc, indent=1, default=_json_default) + "\n"
            )
            log(f"artifacts: {out_dir}/results.json + chaos_check.log")
        if failures:
            log(f"CHAOS FAIL ({len(failures)} failed assertions)")
            return 1
        log("CHAOS PASS")
        return 0

    if args.serve:
        failures: list[str] = []

        def scheck(cond: bool, msg: str) -> None:
            if cond:
                log(f"PASS  {msg}")
            else:
                failures.append(msg)
                log(f"FAIL  {msg}")

        t0 = time.perf_counter()
        arms = run_serve_chaos(args, log, scheck)
        if out_dir is not None:
            doc = {
                "tool": "chaos_check --serve",
                "pass": not failures,
                "config": {
                    k: v for k, v in vars(args).items() if k != "out"
                },
                "wall_s": round(time.perf_counter() - t0, 2),
                "failures": failures,
                **arms,
            }
            out_dir.mkdir(parents=True, exist_ok=True)
            (out_dir / "results.json").write_text(
                json.dumps(doc, indent=1, default=_json_default) + "\n"
            )
            log(f"artifacts: {out_dir}/results.json + chaos_check.log")
        if failures:
            log(f"CHAOS FAIL ({len(failures)} failed assertions)")
            return 1
        log("CHAOS PASS")
        return 0

    if args.segmented:
        failures: list[str] = []

        def check(cond: bool, msg: str) -> None:
            if cond:
                log(f"PASS  {msg}")
            else:
                failures.append(msg)
                log(f"FAIL  {msg}")

        t0 = time.perf_counter()
        tmp_ctx = (
            tempfile.TemporaryDirectory(prefix="jt_segchaos_")
            if args.corpus_dir is None
            else None
        )
        if tmp_ctx is not None:
            args.corpus_dir = tmp_ctx.name
        try:
            run_segmented_chaos(args, log, check)
        finally:
            if tmp_ctx is not None:
                tmp_ctx.cleanup()
        if out_dir is not None:
            doc = {
                "tool": "chaos_check --segmented",
                "pass": not failures,
                "config": {
                    k: v for k, v in vars(args).items() if k != "out"
                },
                "wall_s": round(time.perf_counter() - t0, 2),
                "failures": failures,
            }
            (out_dir / "results.json").write_text(
                json.dumps(doc, indent=1, default=_json_default) + "\n"
            )
            log(f"artifacts: {out_dir}/results.json + chaos_check.log")
        if failures:
            log(f"CHAOS FAIL ({len(failures)} failed assertions)")
            return 1
        log("CHAOS PASS")
        return 0

    if args.global_mesh:
        failures: list[str] = []

        def gcheck(cond: bool, msg: str) -> None:
            if cond:
                log(f"PASS  {msg}")
            else:
                failures.append(msg)
                log(f"FAIL  {msg}")

        t0 = time.perf_counter()
        tmp_ctx = (
            tempfile.TemporaryDirectory(prefix="jt_gmchaos_")
            if args.corpus_dir is None
            else None
        )
        if tmp_ctx is not None:
            args.corpus_dir = tmp_ctx.name
        try:
            arms = run_global_mesh_chaos(args, log, gcheck)
        finally:
            if tmp_ctx is not None:
                tmp_ctx.cleanup()
        if out_dir is not None:
            doc = {
                "tool": "chaos_check --global-mesh",
                "pass": not failures,
                "config": {
                    k: v for k, v in vars(args).items() if k != "out"
                },
                "wall_s": round(time.perf_counter() - t0, 2),
                "failures": failures,
                **arms,
            }
            out_dir.mkdir(parents=True, exist_ok=True)
            (out_dir / "results.json").write_text(
                json.dumps(doc, indent=1, default=_json_default) + "\n"
            )
            log(f"artifacts: {out_dir}/results.json + chaos_check.log")
        if failures:
            log(f"CHAOS FAIL ({len(failures)} failed assertions)")
            return 1
        log("CHAOS PASS")
        return 0

    from jepsen_tpu.parallel.distributed import run_multiprocess_check

    def norm(x):
        return json.loads(json.dumps(x, default=_json_default))

    tmp_ctx = (
        tempfile.TemporaryDirectory(prefix="jt_chaos_")
        if args.corpus_dir is None
        else None
    )
    corpus_dir = (
        Path(tmp_ctx.name) if tmp_ctx else Path(args.corpus_dir)
    )
    failures: list[str] = []

    def check(cond: bool, msg: str) -> None:
        if cond:
            log(f"PASS  {msg}")
        else:
            failures.append(msg)
            log(f"FAIL  {msg}")

    try:
        srcs, poison_pos = _build_corpus(corpus_dir, args, log)
        oracle = _oracle(args, srcs, poison_pos, log)

        state: dict = {"signalled": []}
        if args.mode == "die-env" and args.kill:
            os.environ["JEPSEN_TPU_DIST_DIE_PID"] = ",".join(
                str(q) for q in range(1, 1 + args.kill)
            )
        hook = _nemesis_hook(args, log, state)
        t0 = time.perf_counter()
        try:
            results, info = run_multiprocess_check(
                args.workload,
                srcs,
                args.procs,
                devices_per_proc=args.devices_per_proc,
                chunk=args.chunk,
                timeout_s=args.timeout,
                stripe_timeout_s=args.stripe_timeout,
                _proc_hook=hook,
            )
        finally:
            os.environ.pop("JEPSEN_TPU_DIST_DIE_PID", None)
        wall = time.perf_counter() - t0
        deg = info["degraded"]
        log(
            f"elastic check completed in {wall:.1f}s: "
            f"{len(deg['dead_workers'])} dead, "
            f"{len(deg['requeued_stripes'])} requeued, "
            f"{len(deg['wedged_killed'])} wedge-killed, "
            f"{deg['quarantined_histories']} quarantined histories"
        )

        # -- the differential proof --------------------------------
        key = "stream" if args.workload == "stream" else "queue"
        quarantined_idx = {
            i for i, r in enumerate(results)
            if isinstance(r.get(key), dict) and "quarantined" in r[key]
        }
        mismatches = []
        for i, want in oracle.items():
            if i in quarantined_idx:
                continue  # compared below as honest unknowns
            if norm(results[i]) != norm(want):
                mismatches.append(i)
        check(
            not mismatches,
            f"elastic verdict == {args.oracle} oracle on all "
            f"{len(oracle) - len(quarantined_idx & set(oracle))} "
            f"non-quarantined histories"
            + (f" (MISMATCH at {mismatches[:5]})" if mismatches else ""),
        )
        for i in sorted(poison_pos):
            row = results[i].get(key, {})
            check(
                row.get("valid?") == "unknown"
                and bool(
                    (row.get("quarantined") or {}).get("errors")
                ),
                f"poison history at {i} reports unknown WITH evidence",
            )
        good_quarantined = quarantined_idx - poison_pos
        stripe_q = {
            i
            for q in deg["quarantined_stripes"]
            for i in q["indices"]
        }
        check(
            good_quarantined <= stripe_q,
            f"every quarantined GOOD history "
            f"({len(good_quarantined)}) is accounted for by a "
            f"quarantined stripe in the provenance",
        )
        check(
            deg["quarantined_histories"] >= len(quarantined_idx),
            "provenance quarantine count covers the observed unknowns",
        )
        if args.kill:
            if args.mode == "sigstop":
                check(
                    len(deg["wedged_killed"]) >= 1,
                    f"wedged worker(s) killed by the stripe deadline: "
                    f"{deg['wedged_killed']}",
                )
            check(
                len(deg["dead_workers"]) >= args.kill,
                f"provenance names >= {args.kill} dead worker(s): "
                f"{[(d['pid'], d['rc']) for d in deg['dead_workers']]}",
            )
            check(
                len(deg["requeued_stripes"]) >= 1,
                f"dead workers' stripes were requeued: "
                f"{[(r['stripe'], r['from_pid'], r.get('completed_by')) for r in deg['requeued_stripes']]}",
            )
            check(
                deg["effective_procs"] < args.procs,
                f"reduced worker count recorded "
                f"(effective_procs={deg['effective_procs']})",
            )
        verdict_counts: dict = {}
        for r in results:
            v = str(r.get(key, {}).get("valid?"))
            verdict_counts[v] = verdict_counts.get(v, 0) + 1
        log(f"verdicts: {verdict_counts}")

        if out_dir is not None:
            doc = {
                "tool": "chaos_check",
                "pass": not failures,
                "config": {
                    k: v for k, v in vars(args).items() if k != "out"
                },
                "wall_s": round(wall, 2),
                "histories": len(srcs),
                "poison_positions": sorted(poison_pos),
                "verdict_counts": verdict_counts,
                "quarantined_positions": sorted(quarantined_idx),
                "degraded": deg,
                "per_process": info["per_process"],
                "oracle": args.oracle,
                "failures": failures,
            }
            (out_dir / "results.json").write_text(
                json.dumps(doc, indent=1, default=_json_default) + "\n"
            )
            log(f"artifacts: {out_dir}/results.json + chaos_check.log")
    finally:
        if tmp_ctx is not None:
            tmp_ctx.cleanup()

    if failures:
        log(f"CHAOS FAIL ({len(failures)} failed assertions)")
        return 1
    log("CHAOS PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
