"""Measure the tensor WGL engine on the current JAX backend.

Backs the backend-guidance claim in ``jepsen_tpu/checkers/wgl.py`` with
recorded numbers (compile time + steady-state check time per history
size) instead of a docstring assertion.  Results land in ``WGL_BENCH.md``.

Each size runs in a subprocess with a hard deadline, because the very
thing under measurement is whether XLA compilation of the
while_loop-inside-scan search nest is tractable on the target backend —
a hung compile must produce a row saying so, not hang the bench.

Usage:
  python tools/bench_wgl.py                 # default backend (TPU if any)
  python tools/bench_wgl.py --sizes 8 16 24 --deadline 900
  python tools/bench_wgl.py --one 16        # internal: single measurement
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# runnable from anywhere: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_one(n_ops: int, batch: int, platform: str = "") -> dict:
    import jax

    if platform:
        # config pin beats the sitecustomize env override (env vars alone
        # are too late once the interpreter bootstrapped the plugin path)
        jax.config.update("jax_platforms", platform)

    from jepsen_tpu.checkers.wgl import (
        check_wgl_cpu,
        pack_wgl_batch,
        queue_wgl_ops,
        wgl_tensor_check,
    )
    from jepsen_tpu.history.synth import SynthSpec, synth_batch
    from jepsen_tpu.models.core import UnorderedQueue

    shs = synth_batch(batch, SynthSpec(n_ops=n_ops, n_processes=3))
    opss = [queue_wgl_ops(sh.ops) for sh in shs]
    packed = pack_wgl_batch(opss)
    vs = 32 * max(1, (max(o.call.a0 for ops in opss for o in ops) + 32) // 32)
    model_key = (UnorderedQueue, (vs,))

    t0 = time.perf_counter()
    ok, unknown = wgl_tensor_check(packed, model_key)
    compile_s = time.perf_counter() - t0  # first call: trace + compile + run

    times = []
    for _ in range(3):
        t1 = time.perf_counter()
        ok, unknown = wgl_tensor_check(packed, model_key)
        times.append(time.perf_counter() - t1)
    run_s = min(times)  # best-of: a tunnel hiccup must not inflate the row

    t2 = time.perf_counter()
    for ops in opss:
        check_wgl_cpu(ops, UnorderedQueue(vs))
    cpu_s = (time.perf_counter() - t2) / batch

    return {
        "n_ops": n_ops,
        "batch": batch,
        "backend": jax.default_backend(),
        "compile_s": round(compile_s, 2),
        "run_s": round(run_s, 4),
        "run_per_history_ms": round(run_s / batch * 1e3, 3),
        "cpu_classic_per_history_ms": round(cpu_s * 1e3, 3),
        "all_linearizable": bool(ok.all()),
        "any_unknown": bool(unknown.any()),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", type=int, nargs="+", default=[8, 16, 24])
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--deadline", type=float, default=900.0)
    p.add_argument("--one", type=int, default=0, help="internal")
    p.add_argument(
        "--platform", default="", help="pin backend (e.g. cpu) via jax.config"
    )
    args = p.parse_args()

    if args.one:
        print(json.dumps(measure_one(args.one, args.batch, args.platform)))
        return

    rows = []
    for n in args.sizes:
        cmd = [
            sys.executable, __file__, "--one", str(n),
            "--batch", str(args.batch), "--platform", args.platform,
        ]
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=args.deadline
            )
            if r.returncode == 0:
                row = json.loads(r.stdout.strip().splitlines()[-1])
            else:
                row = {"n_ops": n, "error": r.stderr[-300:]}
        except subprocess.TimeoutExpired:
            row = {
                "n_ops": n,
                "timeout": True,
                "deadline_s": args.deadline,
                "note": "compile did not finish before the deadline",
            }
        row["wall_s"] = round(time.perf_counter() - t0, 1)
        rows.append(row)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
