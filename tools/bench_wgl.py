"""Measure the tensor WGL engine on the current JAX backend.

Backs the backend-guidance claim in ``jepsen_tpu/checkers/wgl.py`` with
recorded numbers (compile time + steady-state check time per history
size) instead of a docstring assertion.  Results land in ``WGL_BENCH.md``.

Each size runs in a subprocess with a hard deadline, because the very
thing under measurement is whether XLA compilation of the
while_loop-inside-scan search nest is tractable on the target backend —
a hung compile must produce a row saying so, not hang the bench.

Usage:
  python tools/bench_wgl.py                 # default backend (TPU if any)
  python tools/bench_wgl.py --sizes 8 16 24 --deadline 900
  python tools/bench_wgl.py --one 16        # internal: single measurement
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# runnable from anywhere: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def hard_history(n_ops: int, window: int, seed: int = 0):
    """Partition-era quorum-queue history (the round-3 hard shape);
    the generator now lives in ``jepsen_tpu.history.synth`` so the
    differential suite (``tests/test_wgl_pcomp.py``) shares it."""
    from jepsen_tpu.history.synth import synth_hard_queue_history

    return synth_hard_queue_history(n_ops, window, seed=seed)


def _enable_cache() -> tuple[str | None, int]:
    """Persistent XLA compile cache under the repo store: the per-bucket
    20–66 s WGL compile is paid once per store, and every later process
    (including these per-row subprocesses) hits it warm (VERDICT r4
    weak #4).  Returns (dir, entry count before compiling).  TPU-only —
    the CPU AOT loader rejects cached entries over machine-feature
    drift (jaxenv docstring); opt back in on CPU for cache-machinery
    tests via JEPSEN_TPU_COMPILE_CACHE=<dir>."""
    import jax

    from jepsen_tpu.utils.jaxenv import (
        COMPILE_CACHE_ENV,
        compile_cache_entries,
        enable_compilation_cache,
    )

    if (
        jax.default_backend() != "tpu"
        and not os.environ.get(COMPILE_CACHE_ENV)
    ):
        return None, 0
    d = enable_compilation_cache(
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "store", "xla_cache",
        )
    )
    return d, compile_cache_entries(d)


def _cache_evidence(row: dict, cache: tuple[str | None, int]) -> dict:
    """compile_cache_hit: the compile added no new cache entry — XLA
    deserialized an existing executable (the warm-cache column)."""
    from jepsen_tpu.utils.jaxenv import compile_cache_entries

    d, before = cache
    if d is not None:
        row["compile_cache_hit"] = compile_cache_entries(d) == before
    return row


def measure_hard(
    n_ops: int, window: int, batch: int, capacity: int, platform: str = "",
    serial: bool = False,
) -> dict:
    """Classic vs tensor on the partition-era shape above.

    Default: the classic host baseline runs on the pipeline executor's
    producer thread (``parallel/pipeline.py``) OVERLAPPED with the
    tensor repeats' device dispatches on this thread — on a chip backend
    the two use different processors, so the row's wall time shrinks by
    ~the classic sweep's length (at w=8 the classic side is the long
    pole).  Per-history classic timing is taken inside the producer, so
    the reported ``classic_per_history_ms`` stays a host-only
    measurement.  ``serial=True`` (--serial; auto on a CPU backend,
    where host and "device" share the cores and overlap would pollute
    both timings) restores the strictly sequential measurement."""
    import jax
    import jax.numpy as jnp

    if platform:
        jax.config.update("jax_platforms", platform)
    cache = _enable_cache()

    from jepsen_tpu.checkers.wgl import (
        check_wgl_cpu,
        pack_wgl_batch,
        queue_wgl_ops,
        wgl_tensor_check,
    )
    from jepsen_tpu.models.core import UnorderedQueue

    opss = [
        queue_wgl_ops(hard_history(n_ops, window, seed=s))
        for s in range(batch)
    ]
    packed = pack_wgl_batch(opss)
    vs = 32 * max(1, (max(o.call.a0 for ops in opss for o in ops) + 32) // 32)
    model_key = (UnorderedQueue, (vs,))
    if jax.default_backend() != "tpu":
        serial = True  # shared cores: overlap would pollute both timings

    t0 = time.perf_counter()
    ok, unknown = wgl_tensor_check(packed, model_key, capacity=capacity)
    compile_s = time.perf_counter() - t0

    def tensor_repeats():
        times = []
        nonlocal_ok = None
        for r in range(3):
            # distinct inputs per repeat: the tunneled remote-execution
            # layer caches repeated (program, args) dispatches (bench.py)
            rolled = type(packed)(
                f=jnp.roll(packed.f, r + 1, axis=0),
                a0=jnp.roll(packed.a0, r + 1, axis=0),
                a1=jnp.roll(packed.a1, r + 1, axis=0),
                ret_op=jnp.roll(packed.ret_op, r + 1, axis=0),
                cands=jnp.roll(packed.cands, r + 1, axis=0),
                cand_overflow=packed.cand_overflow,
                n=packed.n,
            )
            t1 = time.perf_counter()
            got = wgl_tensor_check(rolled, model_key, capacity=capacity)
            times.append(time.perf_counter() - t1)
            nonlocal_ok = got
        return nonlocal_ok, times

    def classic_one(ops):
        t = time.perf_counter()
        r = check_wgl_cpu(ops, UnorderedQueue(vs))
        return r, time.perf_counter() - t

    if serial:
        (ok, unknown), times = tensor_repeats()
        pairs = [classic_one(ops) for ops in opss]
    else:
        from jepsen_tpu.parallel.pipeline import run_pipeline

        tensor_out = []

        def check_stage(item):
            if not tensor_out:  # first item reaching this thread: run
                tensor_out.append(tensor_repeats())  # the device repeats
            return item

        collected, _stats = run_pipeline(
            opss,
            classic_one,  # producer thread: the classic host baseline
            check_stage,
            place=lambda x: x,
            collect=lambda x: x,
            # a bench wants the loud abort, not elastic quarantine —
            # the (result, dt) unpack below cannot absorb a Quarantined
            fail_fast=True,
        )
        (ok, unknown), times = tensor_out[0]
        pairs = collected
    run_s = min(times)
    classic = [r for r, _dt in pairs]
    cpu_s = sum(dt for _r, dt in pairs) / batch

    return _cache_evidence({
        "overlap": "pipeline" if not serial else "serial",
        "n_ops": n_ops,
        "window": window,
        "expected_configs": 2 ** window,
        "capacity": capacity,
        "batch": batch,
        "backend": jax.default_backend(),
        "compile_s": round(compile_s, 2),
        "tensor_per_history_ms": round(run_s / batch * 1e3, 3),
        "classic_per_history_ms": round(cpu_s * 1e3, 3),
        "classic_configs_explored": classic[0]["configs-explored"],
        "all_linearizable": bool(ok.all()),
        "unknown_frac": round(float(unknown.mean()), 3),
        "classic_valid": classic[0]["valid?"],
    }, cache)


def measure_pcomp(
    n_ops: int, window: int, batch: int, platform: str = "",
) -> dict:
    """P-compositional tensor WGL vs the classic host search on the
    partition-era hard shape (the round-6 `wgl_pcomp` table).

    ``pcomp_per_history_ms`` is END-TO-END per history: decomposition +
    bucketed packing + device check + combine (best of 3 full repeats)
    — the honest number, since the decomposition is host work the
    classic search does not pay.  The classic sweep measures
    ``classic_samples`` histories (1 on shapes where its exponential
    tail would blow the row deadline — per-history classic cost is what
    is being measured, and at w≥8 one history is already seconds to
    minutes)."""
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    cache = _enable_cache()

    from jepsen_tpu.checkers.wgl import check_wgl_cpu, queue_wgl_ops
    from jepsen_tpu.checkers.wgl_pcomp import decompose, pcomp_tensor_check
    from jepsen_tpu.models.core import UnorderedQueue

    opss = [
        queue_wgl_ops(hard_history(n_ops, window, seed=s))
        for s in range(batch)
    ]
    vs = 32 * max(1, (max(o.call.a0 for ops in opss for o in ops) + 32) // 32)
    model_key = (UnorderedQueue, (vs,))

    t0 = time.perf_counter()
    decomps = [decompose(ops, model_key) for ops in opss]
    ok, unknown, info = pcomp_tensor_check(decomps)
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(3):
        t1 = time.perf_counter()
        decomps = [decompose(ops, model_key) for ops in opss]
        ok, unknown, info = pcomp_tensor_check(decomps)
        times.append(time.perf_counter() - t1)
    run_s = min(times)

    classic_samples = 1 if (window >= 8 or n_ops >= 1000) else batch
    t2 = time.perf_counter()
    classic = [
        check_wgl_cpu(ops, UnorderedQueue(vs))
        for ops in opss[:classic_samples]
    ]
    cpu_s = (time.perf_counter() - t2) / classic_samples

    pcomp_ms = run_s / batch * 1e3
    classic_ms = cpu_s * 1e3
    return _cache_evidence({
        "engine": "pcomp",
        "n_ops": n_ops,
        "window": window,
        "expected_configs": 2 ** window,
        "batch": batch,
        "backend": jax.default_backend(),
        "compile_s": round(compile_s, 2),
        "pcomp_per_history_ms": round(pcomp_ms, 3),
        "pcomp_subhistories": info[0]["subhistories"],
        "pcomp_sub_capacity": info[0]["max-capacity"],
        "classic_per_history_ms": round(classic_ms, 3),
        "classic_samples": classic_samples,
        "classic_configs_explored": classic[0]["configs-explored"],
        "speedup_vs_classic": round(classic_ms / pcomp_ms, 2),
        "winner": "pcomp" if pcomp_ms < classic_ms else "classic",
        "all_linearizable": bool(ok.all()),
        "unknown_frac": round(float(unknown.mean()), 3),
        "classic_valid": classic[0]["valid?"],
    }, cache)


def measure_one(n_ops: int, batch: int, platform: str = "") -> dict:
    import jax

    if platform:
        # config pin beats the sitecustomize env override (env vars alone
        # are too late once the interpreter bootstrapped the plugin path)
        jax.config.update("jax_platforms", platform)
    cache = _enable_cache()

    from jepsen_tpu.checkers.wgl import (
        check_wgl_cpu,
        pack_wgl_batch,
        queue_wgl_ops,
        wgl_tensor_check,
    )
    from jepsen_tpu.history.synth import SynthSpec, synth_batch
    from jepsen_tpu.models.core import UnorderedQueue

    shs = synth_batch(batch, SynthSpec(n_ops=n_ops, n_processes=3))
    opss = [queue_wgl_ops(sh.ops) for sh in shs]
    packed = pack_wgl_batch(opss)
    vs = 32 * max(1, (max(o.call.a0 for ops in opss for o in ops) + 32) // 32)
    model_key = (UnorderedQueue, (vs,))

    t0 = time.perf_counter()
    ok, unknown = wgl_tensor_check(packed, model_key)
    compile_s = time.perf_counter() - t0  # first call: trace + compile + run

    times = []
    for _ in range(3):
        t1 = time.perf_counter()
        ok, unknown = wgl_tensor_check(packed, model_key)
        times.append(time.perf_counter() - t1)
    run_s = min(times)  # best-of: a tunnel hiccup must not inflate the row

    t2 = time.perf_counter()
    for ops in opss:
        check_wgl_cpu(ops, UnorderedQueue(vs))
    cpu_s = (time.perf_counter() - t2) / batch

    return _cache_evidence({
        "n_ops": n_ops,
        "batch": batch,
        "backend": jax.default_backend(),
        "compile_s": round(compile_s, 2),
        "run_s": round(run_s, 4),
        "run_per_history_ms": round(run_s / batch * 1e3, 3),
        "cpu_classic_per_history_ms": round(cpu_s * 1e3, 3),
        "all_linearizable": bool(ok.all()),
        "any_unknown": bool(unknown.any()),
    }, cache)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", type=int, nargs="+", default=[8, 16, 24])
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--deadline", type=float, default=900.0)
    p.add_argument("--one", type=int, default=0, help="internal")
    p.add_argument(
        "--hard",
        action="store_true",
        help="partition-era crossover sweep: classic vs tensor over "
        "indeterminate-window widths (see hard_history)",
    )
    p.add_argument("--n-ops", type=int, default=200)
    p.add_argument("--windows", type=int, nargs="+", default=[0, 2, 4, 6, 8])
    p.add_argument("--capacity", type=int, default=128)
    p.add_argument("--one-hard", default="", help="internal: nops,window,cap")
    p.add_argument(
        "--platform", default="", help="pin backend (e.g. cpu) via jax.config"
    )
    p.add_argument(
        "--serial",
        action="store_true",
        help="triage escape hatch: strictly sequential classic-vs-tensor "
        "measurement (default on TPU overlaps the classic host sweep "
        "with the device repeats via the pipeline executor; a CPU "
        "backend is always serial — shared cores)",
    )
    p.add_argument(
        "--pcomp",
        action="store_true",
        help="with --hard/--one-hard: measure the P-compositional "
        "tensor engine (checkers/wgl_pcomp.py — per-class narrow "
        "frontiers, capacity ignored/auto-sized per class) against the "
        "classic host search instead of the monolithic tensor engine; "
        "the WGL_BENCH.md round-6 / bench.py `wgl_pcomp` rows",
    )
    args = p.parse_args()

    if args.one_hard:
        n, w, cap = (int(x) for x in args.one_hard.split(","))
        if args.pcomp:
            print(json.dumps(measure_pcomp(n, w, args.batch, args.platform)))
        else:
            print(json.dumps(measure_hard(
                n, w, args.batch, cap, args.platform, serial=args.serial
            )))
        return
    if args.one:
        print(json.dumps(measure_one(args.one, args.batch, args.platform)))
        return

    if args.hard:
        rows = []
        for w in args.windows:
            cmd = [
                sys.executable, __file__,
                "--one-hard", f"{args.n_ops},{w},{args.capacity}",
                "--batch", str(args.batch), "--platform", args.platform,
            ] + (["--serial"] if args.serial else []) + (
                ["--pcomp"] if args.pcomp else []
            )
            t0 = time.perf_counter()
            try:
                r = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=args.deadline
                )
                if r.returncode == 0:
                    row = json.loads(r.stdout.strip().splitlines()[-1])
                else:
                    row = {"window": w, "error": r.stderr[-300:]}
            except subprocess.TimeoutExpired:
                row = {"window": w, "timeout": True, "deadline_s": args.deadline}
            row["wall_s"] = round(time.perf_counter() - t0, 1)
            rows.append(row)
            print(json.dumps(row), flush=True)
        return

    rows = []
    for n in args.sizes:
        cmd = [
            sys.executable, __file__, "--one", str(n),
            "--batch", str(args.batch), "--platform", args.platform,
        ]
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=args.deadline
            )
            if r.returncode == 0:
                row = json.loads(r.stdout.strip().splitlines()[-1])
            else:
                row = {"n_ops": n, "error": r.stderr[-300:]}
        except subprocess.TimeoutExpired:
            row = {
                "n_ops": n,
                "timeout": True,
                "deadline_s": args.deadline,
                "note": "compile did not finish before the deadline",
            }
        row["wall_s"] = round(time.perf_counter() - t0, 1)
        rows.append(row)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
