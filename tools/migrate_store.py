#!/usr/bin/env python
"""Rewrite an existing run store to the ``.jtc`` columnar substrate, in
place.

Pre-format stores (``history.jsonl`` / ``history.edn`` with at most the
legacy npz caches beside them) re-pay a parse on every cold check; this
tool walks a store root and packs each history's sibling ``.jtc``
(``history/columnar.py``) so every later ``check`` / ``bench-check`` /
soak maps bytes straight into staging buffers.

Contract:
- **idempotent** — a history whose ``.jtc`` is already fresh is skipped;
  a second run over a migrated store does zero work;
- **refuses on checksum mismatch** — an existing ``.jtc`` that fails its
  CRC/format validation is reported and NOT overwritten (exit 3): a
  corrupt substrate in a store you asked to migrate is evidence of disk
  trouble, and silently repaving it would destroy that evidence.  Pass
  ``--repave-corrupt`` only once the corruption is understood;
- every written file goes through the shared write-temp → checksum-verify
  → rename discipline (a torn migration can never be installed).

Usage::

    python tools/migrate_store.py STORE_ROOT [--dry-run] [--repave-corrupt]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from jepsen_tpu.history import columnar  # noqa: E402
from jepsen_tpu.history.store import EDN_FILE, HISTORY_FILE  # noqa: E402


def history_sources(root: Path) -> list[Path]:
    """Every history source under ``root``: each ``history.jsonl`` plus
    EDN files that are not an exported twin of a JSONL in the same run
    dir (the CLI's ``_history_paths`` rule)."""
    return sorted(root.glob(f"**/{HISTORY_FILE}")) + [
        p
        for p in sorted(root.glob(f"**/{EDN_FILE}"))
        if not (p.parent / HISTORY_FILE).exists()
    ]


def migrate(
    root: Path, dry_run: bool = False, repave_corrupt: bool = False
) -> dict:
    out = {
        "root": str(root),
        "histories": 0,
        "migrated": 0,
        "fresh": 0,
        "stale_repacked": 0,
        "corrupt_refused": 0,
        "errors": 0,
    }
    for src in history_sources(root):
        out["histories"] += 1
        target = columnar.jtc_path_for(src)
        had = target.exists()
        if had:
            try:
                fresh = columnar.load_jtc(src)
            except columnar.ColumnarFormatError as e:
                if not repave_corrupt:
                    print(
                        f"REFUSED (checksum/format): {target}: {e}",
                        file=sys.stderr,
                    )
                    out["corrupt_refused"] += 1
                    continue
                print(f"# repaving corrupt {target}: {e}", file=sys.stderr)
                fresh = None
            if fresh is not None:
                out["fresh"] += 1
                continue
        if dry_run:
            out["migrated"] += 1
            continue
        try:
            columnar.pack_jtc(src)
        except Exception as e:  # noqa: BLE001 - per-file, reported
            print(
                f"ERROR packing {src}: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
            out["errors"] += 1
            continue
        out["migrated"] += 1
        if had:
            out["stale_repacked"] += 1
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("root", help="store root (e.g. store/ or one run dir)")
    p.add_argument(
        "--dry-run", action="store_true",
        help="report what would be packed without writing anything",
    )
    p.add_argument(
        "--repave-corrupt", action="store_true",
        help="overwrite a .jtc that fails checksum/format validation "
        "(default: refuse and exit 3 — see the module docstring)",
    )
    args = p.parse_args(argv)
    root = Path(args.root)
    if not root.exists():
        print(f"no such store root: {root}", file=sys.stderr)
        return 2
    out = migrate(
        root, dry_run=args.dry_run, repave_corrupt=args.repave_corrupt
    )
    out["dry_run"] = args.dry_run
    print(json.dumps(out))
    if out["corrupt_refused"]:
        return 3
    return 1 if out["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
