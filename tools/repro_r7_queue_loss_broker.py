"""Broker-layer repro driver for the open r7 durable-queue acked-loss
(companion to ``repro_r7_queue_loss.py``, which exonerated the bare
replication layer: a 20-seed window sweep with broker-faithful sweep
draining lost nothing).

This one replays the suspect window through the REAL delivery plane —
in-process ``MiniAmqpBroker`` cluster over durable Raft backends, native
C++ AMQP clients on real TCP sockets (confirmed publishes, asynchronous
ack-mode consumers) — while the cluster takes partitions, a membership
remove(+wipe)+rejoin, and kills with durable restarts; then drains.  A
confirmed publish that no consumer ever saw and the final drain cannot
produce is a LOSS.

Usage::

    python tools/repro_r7_queue_loss_broker.py --seeds 0 9 --minutes 0.5
"""

from __future__ import annotations

import argparse
import os
import shutil
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jepsen_tpu.harness.broker import MiniAmqpBroker  # noqa: E402
from jepsen_tpu.harness.replication import ReplicatedBackend  # noqa: E402

FAST = dict(
    election_timeout=(0.15, 0.3),
    heartbeat_s=0.04,
    dead_owner_s=0.8,
    submit_timeout_s=2.0,
)


_next_port = [14000]


def _free_port() -> int:
    """A listener port OUTSIDE the ephemeral range (16000-65535 on this
    image): kernel-assigned local ports of the drivers' reconnect storms
    must never collide with a broker/Raft port we re-bind after a kill."""
    while _next_port[0] < 16000:
        port = _next_port[0]
        _next_port[0] += 1
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", port))
                return port
        except OSError:
            continue
    raise RuntimeError("no free low port")


class BrokerCluster:
    def __init__(self, root: str, n: int = 5, seed: int = 0):
        self.root = root
        self.seed = seed
        self.names = [f"n{i}" for i in range(n)]
        self.repl_peers = {nm: ("127.0.0.1", _free_port())
                           for nm in self.names}
        self.amqp_ports = {nm: _free_port() for nm in self.names}
        self.brokers: dict[str, MiniAmqpBroker | None] = {}
        self.blocked: set[frozenset] = set()
        for i, nm in enumerate(self.names):
            self._boot(nm, fresh=False, first=True, idx=i)

    def _dir(self, nm: str) -> str:
        return os.path.join(self.root, nm)

    def _boot(self, nm: str, fresh: bool, first: bool = False,
              idx: int = 0) -> None:
        for attempt in range(80):
            try:
                backend = ReplicatedBackend(
                    nm,
                    {nm: self.repl_peers[nm]} if fresh else self.repl_peers,
                    data_dir=self._dir(nm),
                    bootstrap=not fresh,
                    rng_seed=self.seed * 100 + idx,
                    **FAST,
                )
                break
            except OSError:  # ephemeral-port collision; see sibling tool
                if attempt == 79:
                    raise
                time.sleep(0.25)
        for attempt in range(80):
            try:
                self.brokers[nm] = MiniAmqpBroker(
                    port=self.amqp_ports[nm], replication=backend
                ).start()
                break
            except OSError:
                if attempt == 79:
                    raise
                time.sleep(0.25)
        self._apply_blocks()

    def leader(self, timeout=10.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for nm, b in self.brokers.items():
                if b is not None and b.replication.raft.is_leader():
                    return nm
            time.sleep(0.02)
        raise AssertionError("no leader")

    def alive(self) -> list[str]:
        return [nm for nm, b in self.brokers.items() if b is not None]

    def kill(self, nm: str) -> None:
        b = self.brokers[nm]
        if b is not None:
            b.stop()
        self.brokers[nm] = None

    def restart(self, nm: str, fresh: bool = False) -> None:
        if fresh:
            shutil.rmtree(self._dir(nm), ignore_errors=True)
        self._boot(nm, fresh=fresh)

    def forget(self, nm: str, via: str) -> bool:
        ok = self.brokers[via].replication.raft.request_forget(
            nm, timeout_s=8.0
        )
        if ok:
            shutil.rmtree(self._dir(nm), ignore_errors=True)
        return ok

    def join(self, nm: str, via: str) -> bool:
        return self.brokers[nm].replication.raft.request_join(
            self.repl_peers[via], timeout_s=8.0
        )

    def partition(self, side_a, side_b) -> None:
        for a in side_a:
            for b in side_b:
                self.blocked.add(frozenset((a, b)))
        self._apply_blocks()

    def heal(self) -> None:
        self.blocked.clear()
        for b in self.brokers.values():
            if b is not None:
                b.replication.raft.unblock_all()

    def _apply_blocks(self) -> None:
        for nm, b in self.brokers.items():
            if b is None:
                continue
            b.replication.raft.unblock_all()
            for link in self.blocked:
                if nm in link:
                    (other,) = link - {nm}
                    b.replication.raft.block(other)

    def stop(self) -> None:
        for b in self.brokers.values():
            if b is not None:
                b.stop()


def run_window(native, seed: int, minutes: float) -> dict:
    import random

    rng = random.Random(seed)
    root = tempfile.mkdtemp(prefix=f"repro_r7b_{seed}_")
    c = BrokerCluster(root, seed=seed)
    acked: list[int] = []
    seen: set[int] = set()
    stop = threading.Event()
    next_v = [0]

    c.leader(timeout=30.0)

    def _setup(d) -> bool:
        for _ in range(20):
            if stop.is_set():
                return False
            try:
                d.setup()
                return True
            except Exception:
                time.sleep(0.25)
        return False

    # full host:port node list, like the real localcluster: the drain
    # choreography visits EVERY registered host
    all_hosts = [f"127.0.0.1:{c.amqp_ports[nm]}" for nm in c.names]

    def publisher(i: int):
        nm = c.names[i % len(c.names)]
        d = native.NativeQueueDriver(
            all_hosts, "127.0.0.1", port=c.amqp_ports[nm],
            connect_retry_ms=2000,
        )
        if not _setup(d):
            return
        while not stop.is_set():
            v = next_v[0]
            next_v[0] += 1
            try:
                if d.enqueue(v, 2.0) is True:
                    acked.append(v)
            except Exception:
                time.sleep(0.05)
        try:
            d.close()
        except Exception:
            pass

    def consumer(i: int):
        nm = c.names[(i + 2) % len(c.names)]
        d = native.NativeQueueDriver(
            all_hosts, "127.0.0.1", port=c.amqp_ports[nm],
            consumer_type="asynchronous", connect_retry_ms=2000,
        )
        if not _setup(d):
            return
        while not stop.is_set():
            try:
                got = d.dequeue(1.0)
                if got is not None:
                    seen.add(int(got))
            except Exception:
                time.sleep(0.05)
        try:
            d.close()
        except Exception:
            pass

    threads = [
        threading.Thread(target=publisher, args=(i,), daemon=True)
        for i in range(2)
    ] + [
        threading.Thread(target=consumer, args=(i,), daemon=True)
        for i in range(2)
    ]
    for t in threads:
        t.start()

    events = []
    t_end = time.monotonic() + minutes * 60.0
    try:
        while time.monotonic() < t_end:
            names = list(c.names)
            rng.shuffle(names)
            side_a, side_b = names[:2], names[2:]
            c.partition(side_a, side_b)
            events.append(f"partition {side_a}|{side_b}")
            time.sleep(rng.uniform(0.5, 1.5))
            c.heal()

            victim = rng.choice(c.alive())
            c.kill(victim)
            ok = False
            for via in c.alive():
                try:
                    ok = c.forget(victim, via)
                except Exception:
                    ok = False
                if ok:
                    break
            events.append(f"forget {victim} ok={ok}")
            c.restart(victim, fresh=ok)
            if ok:
                joined = c.join(victim, rng.choice(
                    [n for n in c.alive() if n != victim]
                ))
                events.append(f"join {victim} ok={joined}")
            time.sleep(rng.uniform(0.0, 0.4))
            other = rng.choice([n for n in c.alive() if n != victim])
            c.kill(other)
            events.append(f"kill {other}")
            time.sleep(rng.uniform(0.2, 1.0))
            c.restart(other)
            events.append(f"restart {other}")
            time.sleep(rng.uniform(0.5, 1.0))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=3.0)
        c.heal()

    post: dict = {}
    try:
        lead = c.leader(timeout=12.0)
        d = native.NativeQueueDriver(
            [f"127.0.0.1:{c.amqp_ports[nm]}" for nm in c.names],
            "127.0.0.1", port=c.amqp_ports[lead],
            connect_retry_ms=3000,
        )
        d.setup()
        deadline = time.monotonic() + 60.0
        stable_empty = 0
        while stable_empty < 3 and time.monotonic() < deadline:
            got = d.drain()
            if got:
                stable_empty = 0
                seen.update(int(v) for v in got)
            else:
                stable_empty += 1
                time.sleep(1.0)
        try:
            d.close()
        except Exception:
            pass
        lost_now = sorted(set(acked) - seen)
        if lost_now:
            b = c.brokers[lead]
            with b.replication.machine.lock:
                inflight = {}
                for mid, (o, _q, m) in b.replication.machine.inflight.items():
                    try:
                        inflight[int(m.body.decode())] = o
                    except ValueError:
                        pass
                ready = set()
                for dq in b.replication.machine.queues.values():
                    for m in dq:
                        try:
                            ready.add(int(m.body.decode()))
                        except ValueError:
                            pass
            for v in lost_now:
                post[v] = {
                    "inflight_owner": inflight.get(v),
                    "ready": v in ready,
                }
    finally:
        c.stop()
        shutil.rmtree(root, ignore_errors=True)

    return {
        "seed": seed,
        "acked": len(acked),
        "seen": len(seen),
        "lost": sorted(set(acked) - seen),
        "post": post,
        "events": events,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, nargs=2, default=[0, 9])
    p.add_argument("--minutes", type=float, default=0.5)
    args = p.parse_args()

    from jepsen_tpu.client import native

    native.load_library().amqp_set_logging(0)
    bad = 0
    for seed in range(args.seeds[0], args.seeds[1] + 1):
        native.reset(drain_wait_ms=200)
        try:
            r = run_window(native, seed, minutes=args.minutes)
        except Exception as e:  # noqa: BLE001 - a broken seed is reported
            print(f"seed {seed}: HARNESS ERROR {type(e).__name__}: {e}")
            continue
        status = "LOST" if r["lost"] else "ok"
        print(
            f"seed {seed}: {status} acked={r['acked']} seen={r['seen']}"
            + (f" lost={r['lost'][:20]}" if r["lost"] else ""),
            flush=True,
        )
        if r["lost"]:
            bad += 1
            print(f"  post-mortem: {r['post']}")
            for e in r["events"]:
                print(f"  {e}")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
