#!/usr/bin/env python
"""Load generator + chaos proofs for the always-on verification
service (ISSUE 16): the availability story, measured fail-loud.

Four arms, each against a fresh in-process :class:`IngestService`
(the wire adds a socket hop; admission, backpressure, carry and
recovery semantics — the claims under test — live in the core):

- **throughput** (cache OFF): ``--histories`` one-shot submissions
  through the streaming admission path, reporting admitted
  histories/s and p50/p99 submit→verdict latency off the PR-9
  mergeable quantile sketches (``service.submit_to_verdict_s``).
- **cache**: one history submitted cold, then re-requested by its
  content key; the content-addressed verdict cache must answer
  ``--cache-reps`` lookups at ≥100x below the cold check cost.
- **chaos**: a zero-kill honesty row first (``worker_deaths == 0``
  and NO verdict claims recovery), then the deterministic die-hook
  kills worker 0 mid-feed under concurrent streams: every
  non-quarantined verdict must be IDENTICAL to the serial
  :class:`SegmentedChecker` oracle and the affected stream's
  ``degraded`` provenance must name the dead worker.
- **saturation**: a deliberately tiny service (1 slow worker, ingress
  cap 4) under a burst; every refused submit must be a loud
  ``SATURATED`` reject and the books must balance exactly:
  ``submitted == verdicts + rejects`` with zero quarantines, zero
  gapped carries, zero silent drops.

A fifth arm, ``--batching`` (ISSUE 20), measures the continuous
batcher: coalescing ON vs OFF at {1, 8, 64} concurrent small-segment
streams, admitted→verdict throughput, p50/p99 added latency off the
``service.batch_coalesce_s`` sketch, batch fill fraction, and zero
verdict divergence against the serial oracle — both sub-arms pay real
per-segment device dispatch so OFF is the honest under-batching
baseline, not a strawman.

Artifacts land in ``--out``: ``bench_serve.log`` + ``results.json``
(the committed evidence for the round).  Exit 0 only if every
assertion held.  ``bench.py`` runs a scaled-down pass as its
``serve`` section (offline-schema-gated in tests/test_ci.py).

Examples:
  JAX_PLATFORMS=cpu python tools/bench_serve.py --out store/bench_r16_serve
  JAX_PLATFORMS=cpu python tools/bench_serve.py --histories 20000 \
      --workers 4 --out /tmp/serve_big
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


class _Log:
    def __init__(self, path: Path | None):
        self.path = path
        if path is not None:
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text("")

    def __call__(self, msg: str) -> None:
        line = f"[{time.strftime('%H:%M:%S')}] {msg}"
        print(line, flush=True)
        if self.path is not None:
            with open(self.path, "a") as fh:
                fh.write(line + "\n")


def _corpus_rows(n_histories: int, n_base: int, n_ops: int, seed: int):
    """``n_base`` distinct synthesized queue histories (one laced with
    a known loss so the corpus carries a real invalid verdict),
    replicated to ``n_histories`` row blocks."""
    from jepsen_tpu.history.rows import _rows_for
    from jepsen_tpu.history.synth import SynthSpec, synth_history

    base = []
    for i in range(n_base):
        h = synth_history(
            SynthSpec(n_ops=n_ops, seed=seed + i, lost=1 if i == 0 else 0)
        )
        base.append((_rows_for(h.ops), len(h.ops)))
    return [base[i % n_base] for i in range(n_histories)]


def _oracle_verdict(rows: np.ndarray, n_ops: int) -> dict:
    from jepsen_tpu.checkers.segmented import SegmentedChecker

    eng = SegmentedChecker("queue", device=False)
    eng.feed_rows(rows, n_ops)
    return eng.finish()


def _families_equal(served: dict, oracle: dict) -> bool:
    """Wire verdicts carry sorted lists where the engine carries sets;
    compare on the wire-normalized shape, families + validity only
    (provenance/degraded/segmented metadata legitimately differ)."""
    from jepsen_tpu.service.stream import _wire_safe

    o = _wire_safe(oracle)
    keys = set(o) - {"segmented"}
    s = {k: served.get(k) for k in keys}
    return s == {k: o[k] for k in keys}


def _new_service(registry, **kw):
    from jepsen_tpu.service.stream import IngestService

    kw.setdefault("device", False)  # CPU numpy twins: the bench must
    # measure the service, not per-block dispatch overhead on the
    # CPU backend (chip runs flip this via --device)
    return IngestService(registry=registry, **kw)


def _drain_submits(svc, ids, timeout_s: float) -> dict:
    got = svc.collect(ids, timeout=timeout_s)
    if got["pending"]:
        raise RuntimeError(
            f"{len(got['pending'])} submissions never completed "
            f"within {timeout_s}s"
        )
    return got["done"]


# -- arms -----------------------------------------------------------------


def arm_throughput(args, log) -> dict:
    from jepsen_tpu.obs.metrics import Registry

    corpus = _corpus_rows(args.histories, args.base, args.ops, args.seed)
    reg = Registry()
    svc = _new_service(
        reg, workers=args.workers, max_streams=args.histories + 8,
        ingress_cap=args.histories + 8, cache=None, device=args.device,
    )
    try:
        t0 = time.perf_counter()
        ids = []
        rejects = 0
        for rows, n_ops in corpus:
            while True:
                rep = svc.submit("queue", None, "rows", rows, n_ops)
                if rep["op"] == "accepted":
                    ids.append(rep["id"])
                    break
                rejects += 1  # honest backpressure: re-offer
                time.sleep(0.001)
        admit_wall = time.perf_counter() - t0
        verdicts = _drain_submits(svc, ids, args.timeout)
        wall = time.perf_counter() - t0
    finally:
        svc.close()
    sk = reg.sketch("service.submit_to_verdict_s")
    out = {
        "histories": len(corpus),
        "ops_per_history": args.ops,
        "workers": args.workers,
        # two rates, both real: ADMISSION is the subsystem under test
        # (the acceptance floor); verdict completion is engine-bound
        # (the host numpy twins here — chip runs batch the per-block
        # dispatch) and governed by backpressure, never a silent queue
        "admit_wall_s": round(admit_wall, 3),
        "admitted_per_s": round(len(corpus) / admit_wall, 1),
        "wall_s": round(wall, 3),
        "completed_per_s": round(len(verdicts) / wall, 1),
        "submit_rejects_retried": rejects,
        "p50_ms": round(sk.quantile(0.5) * 1e3, 3),
        "p99_ms": round(sk.quantile(0.99) * 1e3, 3),
        "verdicts": len(verdicts),
    }
    log(f"throughput: {json.dumps(out)}")
    return out


def arm_cache(args, log) -> dict:
    from jepsen_tpu.obs.metrics import Registry
    from jepsen_tpu.service.cache import VerdictCache

    rows, n_ops = _corpus_rows(1, 1, args.cache_ops, args.seed + 100)[0]
    key = hashlib.sha256(
        np.ascontiguousarray(rows).tobytes()
    ).hexdigest()
    reg = Registry()
    svc = _new_service(
        reg, workers=1, cache=VerdictCache(64, registry=reg),
        device=args.device,
    )
    try:
        t0 = time.perf_counter()
        rep = svc.submit("queue", None, "rows", rows, n_ops)
        assert rep["op"] == "accepted", rep
        verdicts = _drain_submits(svc, [rep["id"]], args.timeout)
        cold_s = time.perf_counter() - t0
        cold = verdicts[rep["id"]]

        t1 = time.perf_counter()
        hits = 0
        for _ in range(args.cache_reps):
            r = svc.open("queue", None, content_key=key)
            assert r["op"] == "cached", (
                f"content-addressed lookup missed: {r}"
            )
            hits += 1
        hit_s = (time.perf_counter() - t1) / max(hits, 1)
    finally:
        svc.close()
    assert _families_equal(r["verdict"], cold), (
        "cached verdict drifted from the served one"
    )
    out = {
        "ops": args.cache_ops,
        "cold_check_s": round(cold_s, 4),
        "cached_lookup_s": round(hit_s, 7),
        "reps": args.cache_reps,
        "speedup": round(cold_s / max(hit_s, 1e-9), 1),
        "speedup_ge_100x": cold_s / max(hit_s, 1e-9) >= 100.0,
        "cache": svc.cache.stats(),
    }
    log(f"cache: {json.dumps(out)}")
    return out


def _run_streams(svc, corpus, block_rows: int, timeout_s: float):
    """Feed each history as a multi-block stream (re-offering on
    SATURATED), then finish all.  Returns [(sid, verdict, oracle)]."""
    from jepsen_tpu.history.columnar import iter_row_blocks

    opened = []
    for rows, n_ops in corpus:
        r = svc.open("queue", None, kind="stream", deadline_s=timeout_s)
        assert r["op"] == "opened", r
        opened.append((r["stream"], rows, n_ops))
    for sid, rows, n_ops in opened:
        for seq, (blk, b_ops) in enumerate(
            iter_row_blocks(rows, block_rows)
        ):
            while True:
                rep = svc.feed(sid, seq, "rows", blk, b_ops)
                if rep["op"] != "rejected":
                    break
                time.sleep(0.002)  # honest backpressure
            assert rep["op"] == "accepted", rep
    return [
        (sid, svc.finish(sid, timeout=timeout_s),
         _oracle_verdict(rows, n_ops))
        for sid, rows, n_ops in opened
    ]


def arm_chaos(args, log, check) -> dict:
    from jepsen_tpu.obs.metrics import Registry

    corpus = _corpus_rows(
        args.chaos_streams, min(args.base, args.chaos_streams),
        args.chaos_ops, args.seed + 200,
    )
    block_rows = max(64, (2 * args.chaos_ops) // args.chaos_blocks)

    # honesty row: an UNKILLED run may never wear the recovery story
    reg0 = Registry()
    svc0 = _new_service(reg0, workers=args.workers, device=args.device)
    try:
        clean = _run_streams(svc0, corpus, block_rows, args.timeout)
        stats0 = svc0.stats()
    finally:
        svc0.close()
    zero_kill = {
        "streams": len(clean),
        "worker_deaths": stats0["worker_deaths"],
        "block_requeues": stats0["block_requeues"],
        "claims_recovery": any("degraded" in v for _s, v, _o in clean),
        "verdicts_match": all(
            _families_equal(v, o) for _s, v, o in clean
        ),
    }
    check(zero_kill["worker_deaths"] == 0,
          "zero-kill run recorded zero worker deaths")
    check(not zero_kill["claims_recovery"],
          "zero-kill run claims NO recovery (no degraded verdicts)")
    check(zero_kill["verdicts_match"],
          "zero-kill verdicts identical to the serial oracle")

    # the kill: worker 0 dies mid-feed of its Nth block, concurrent
    # streams in flight — the spool/requeue protocol under live load
    reg = Registry()
    svc = _new_service(
        reg, workers=args.workers, device=args.device,
        die_after=(0, args.kill_block),
    )
    try:
        served = _run_streams(svc, corpus, block_rows, args.timeout)
        stats = svc.stats()
    finally:
        svc.close()
    quarantined = [
        (s, v) for s, v, _o in served if v.get("valid?") == "unknown"
        and "quarantined" in str(v)
    ]
    survivors = [
        (s, v, o) for s, v, o in served if (s, v) not in quarantined
    ]
    mism = [s for s, v, o in survivors if not _families_equal(v, o)]
    degraded = [
        (s, v["degraded"]) for s, v, _o in served if "degraded" in v
    ]
    check(stats["worker_deaths"] >= 1,
          f"die-hook fired (worker_deaths={stats['worker_deaths']})")
    check(not mism,
          f"every non-quarantined verdict identical to the oracle "
          f"(mismatches: {mism or 'none'})")
    check(len(degraded) >= 1,
          f"killed stream(s) carry degraded provenance "
          f"({len(degraded)} streams)")
    check(
        all(d.get("dead_workers") for _s, d in degraded),
        "degraded provenance NAMES the dead worker",
    )
    out = {
        "zero_kill": zero_kill,
        "kill": {
            "streams": len(served),
            "kill_block": args.kill_block,
            "worker_deaths": stats["worker_deaths"],
            "block_requeues": stats["block_requeues"],
            "workers_alive": stats["workers_alive"],
            "quarantined": len(quarantined),
            "degraded_streams": len(degraded),
            "degraded_example": degraded[0][1] if degraded else None,
            "oracle_mismatches": len(mism),
        },
    }
    log(f"chaos: {json.dumps(out)}")
    return out


def arm_saturation(args, log, check) -> dict:
    from jepsen_tpu.obs.metrics import Registry

    corpus = _corpus_rows(
        args.sat_submits, 4, args.ops, args.seed + 300
    )
    reg = Registry()
    svc = _new_service(
        reg, workers=1, max_streams=args.sat_submits + 4, ingress_cap=4,
        block_delay_s=args.sat_block_delay, cache=None,
        device=args.device,
    )
    try:
        ids, rejects = [], 0
        for rows, n_ops in corpus:  # a burst, no pacing, no retries
            rep = svc.submit("queue", None, "rows", rows, n_ops)
            if rep["op"] == "accepted":
                ids.append(rep["id"])
            else:
                assert rep["op"] == "rejected" and rep["reason"], rep
                rejects += 1
        verdicts = _drain_submits(svc, ids, args.timeout)
        stats = svc.stats()
    finally:
        svc.close()
    gapped = sum(
        1 for v in verdicts.values() if "gap" in str(v.get("queue", ""))
        or "gap" in str(v.get("quarantined", ""))
    )
    quar = sum(
        1 for v in verdicts.values() if v.get("valid?") == "unknown"
    )
    out = {
        "submitted": len(corpus),
        "accepted": len(ids),
        "rejected_saturated": rejects,
        "verdicts": len(verdicts),
        "quarantines": quar,
        "gapped_carries": gapped,
        "silent_drops": len(corpus) - len(verdicts) - rejects,
        "ingress_cap": 4,
        "admission_rejects": stats["admission_rejects"],
    }
    check(rejects > 0,
          f"the burst actually saturated ({rejects} SATURATED rejects)")
    check(out["silent_drops"] == 0,
          "books balance: submitted == verdicts + rejects "
          f"({out['submitted']} == {out['verdicts']} + "
          f"{out['rejected_saturated']})")
    check(out["gapped_carries"] == 0, "zero gapped carries")
    check(out["quarantines"] == 0,
          "saturation produced rejects, never quarantines")
    log(f"saturation: {json.dumps(out)}")
    return out


def _sample_buckets(corpus, block_rows):
    """The ``(L, V)`` shape buckets this corpus will actually dispatch
    — sampled by running the host prep over a few histories so the
    warmup set is honest (covers real dispatch shapes, not guesses)."""
    from jepsen_tpu.checkers.segmented import queue_prepare_rows
    from jepsen_tpu.history.columnar import iter_row_blocks

    keys = set()
    for rows, _n in corpus[: min(4, len(corpus))]:
        for blk, _b in iter_row_blocks(rows, block_rows):
            prep = queue_prepare_rows(blk, blk[:, 0].astype(np.int64))
            if prep is not None:
                keys.add((int(prep["L"]), int(prep["V"])))
    return tuple(sorted(keys)) or ((128, 128),)


def _batching_round(args, n_streams: int, batch_on: bool, corpus,
                    block_rows: int, pace_rate: float | None = None) -> dict:
    """One measured pass: ``n_streams`` concurrent streams of small
    segments fed round-robin (cross-stream material for the coalescer),
    admitted→verdict wall clock, every verdict diffed against the
    serial oracle.  Both arms pay real per-segment device dispatch
    (``device=True``) — OFF is the documented under-batching failure
    mode, ON routes the same blocks through the continuous batcher.

    ``pace_rate`` (blocks/s) throttles the producers: the latency
    probe runs below measured capacity so the coalesce sketch reads
    the SCHEDULER's hold time, not saturation queueing (at saturating
    offered load any queue's delay is set by Little's law, which says
    nothing about the batching deadline)."""
    from jepsen_tpu.history.columnar import iter_row_blocks
    from jepsen_tpu.obs.metrics import Registry

    reg = Registry()
    kw = dict(
        workers=args.workers, max_streams=n_streams + 8,
        ingress_cap=max(256, 4 * n_streams * args.target_batch),
        cache=None, device=True,
    )
    if batch_on:
        kw.update(
            batch=True, target_batch=args.target_batch,
            max_batch_wait_ms=args.max_batch_wait_ms,
            warmup=True,
            warmup_buckets=_sample_buckets(corpus, block_rows),
        )
    if not batch_on:
        # pre-compile the per-segment device program outside the timed
        # window — the OFF baseline measures steady-state dispatch
        # overhead, not one-time XLA compile (ON pays its compile in
        # warmup, also untimed)
        from jepsen_tpu.checkers.segmented import SegmentedChecker
        from jepsen_tpu.history.columnar import iter_row_blocks as _irb

        eng = SegmentedChecker("queue", device=True)
        blk, b_ops = next(_irb(corpus[0][0], block_rows))
        eng.feed_rows(blk, b_ops)
    svc = _new_service(reg, **kw)
    try:
        feeds = []
        for rows, n_ops in corpus:
            r = svc.open("queue", None, kind="stream",
                         deadline_s=args.timeout)
            assert r["op"] == "opened", r
            feeds.append(
                (r["stream"], list(iter_row_blocks(rows, block_rows)))
            )
        total = sum(len(blocks) for _sid, blocks in feeds)
        done = [0] * len(feeds)
        fed = 0
        t0 = time.perf_counter()
        while fed < total:  # round-robin: interleave the streams
            stalled = True
            for i, (sid, blocks) in enumerate(feeds):
                if done[i] >= len(blocks):
                    continue
                if pace_rate:
                    tgt = t0 + fed / pace_rate
                    now = time.perf_counter()
                    if tgt > now:
                        time.sleep(tgt - now)
                blk, b_ops = blocks[done[i]]
                rep = svc.feed(sid, done[i], "rows", blk, b_ops)
                if rep["op"] == "rejected":
                    continue  # honest backpressure: re-offer next lap
                assert rep["op"] == "accepted", rep
                done[i] += 1
                fed += 1
                stalled = False
            if stalled:
                time.sleep(0.001)
        verdicts = [
            (sid, svc.finish(sid, timeout=args.timeout))
            for sid, _blocks in feeds
        ]
        wall = time.perf_counter() - t0
        stats = svc.stats()
    finally:
        svc.close()
    mism = sum(
        1
        for (sid, v), (rows, n_ops) in zip(verdicts, corpus)
        if not _families_equal(v, _oracle_verdict(rows, n_ops))
    )
    out = {
        "streams": n_streams,
        "blocks": total,
        "wall_s": round(wall, 3),
        "blocks_per_s": round(total / wall, 1),
        "oracle_mismatches": mism,
        "quarantines": sum(
            1 for _s, v in verdicts if v.get("valid?") == "unknown"
        ),
    }
    if batch_on:
        bat = stats.get("batcher") or {}
        co = reg.sketch("service.batch_coalesce_s")
        fill = reg.sketch("service.batch_fill")
        out.update(
            launches=bat.get("launches"),
            batched_blocks=bat.get("batched_blocks"),
            salvages=bat.get("salvages"),
            warmup_hits=bat.get("warmup_hits"),
            warmup_misses=bat.get("warmup_misses"),
            evictions=bat.get("evictions"),
            fill_fraction=(
                round(fill.sum / fill.count, 3) if fill.count else None
            ),
            # the coalesce sketch: time a segment sat parked before
            # its super-batch launched — scheduler hold time when the
            # round is paced below capacity, saturation queueing when
            # it is not (reported under the honest name either way)
            coalesce_p50_ms=(
                round(co.quantile(0.5) * 1e3, 3) if co.count else 0.0
            ),
            coalesce_p99_ms=(
                round(co.quantile(0.99) * 1e3, 3) if co.count else 0.0
            ),
        )
    return out


def run_batching(args, log, check) -> dict:
    """The continuous-batching arm (ISSUE 20): coalescing ON vs OFF at
    {1, 8, N} concurrent streams of small segments.  Correctness checks
    (zero oracle divergence, warmup hit, no salvages) apply at every
    level; the throughput/fill/latency gates apply only at levels with
    ``>= --bat-gate-streams`` streams — under-batching only costs when
    concurrency is real, and tiny CI runs must not gate on speed."""
    n_ops = max(64, (args.bat_block_rows * args.bat_blocks) // 2)
    corpus = _corpus_rows(
        args.bat_streams, min(args.base, 8), n_ops, args.seed + 400
    )
    doc: dict = {
        "target_batch": args.target_batch,
        "max_batch_wait_ms": args.max_batch_wait_ms,
        "block_rows": args.bat_block_rows,
        "ops_per_stream": n_ops,
        "workers": args.workers,
        "levels": [],
    }
    probe_corpus = _corpus_rows(
        args.bat_streams, min(args.base, 8), max(64, n_ops // 4),
        args.seed + 401,
    )
    levels = sorted({1, min(8, args.bat_streams), args.bat_streams})
    for n in levels:
        sub = corpus[:n]
        off = _batching_round(args, n, False, sub, args.bat_block_rows)
        on = _batching_round(args, n, True, sub, args.bat_block_rows)
        # the latency probe: same shape of load, paced to a fraction
        # of the measured ON capacity — below saturation the coalesce
        # sketch reads what the SCHEDULER added (park-until-launch),
        # which is the p50/p99 added latency the budget gate is about
        probe_rate = args.bat_probe_load * on["blocks_per_s"]
        probe = _batching_round(
            args, n, True, probe_corpus[:n], args.bat_block_rows,
            pace_rate=probe_rate,
        )
        on["added_p50_ms"] = probe["coalesce_p50_ms"]
        on["added_p99_ms"] = probe["coalesce_p99_ms"]
        on["probe"] = {
            "pace_blocks_per_s": round(probe_rate, 1),
            "blocks": probe["blocks"],
            "oracle_mismatches": probe["oracle_mismatches"],
            "fill_fraction": probe["fill_fraction"],
        }
        level = {
            "streams": n, "off": off, "on": on,
            "speedup": round(
                on["blocks_per_s"] / max(off["blocks_per_s"], 1e-9), 2
            ),
        }
        doc["levels"].append(level)
        log(f"serve_batching[{n} streams]: {json.dumps(level)}")
        check(
            off["oracle_mismatches"] == 0 and on["oracle_mismatches"] == 0
            and on["probe"]["oracle_mismatches"] == 0,
            f"[{n} streams] zero verdict divergence vs the serial "
            f"oracle (both arms + paced probe)",
        )
        check(
            on["quarantines"] == 0 and off["quarantines"] == 0,
            f"[{n} streams] no quarantines under clean load",
        )
        check(
            (on.get("warmup_hits") or 0) >= 1,
            f"[{n} streams] warmed bucket hit on first dispatch "
            f"(no compile spike on the latency path)",
        )
        check(
            (on.get("salvages") or 0) == 0,
            f"[{n} streams] zero salvage fallbacks (coalesced path "
            f"served every block)",
        )
        # real coalescing: mean entries per launch beats OFF's
        # one-block-per-dispatch degenerate "fill"
        batch_w = 1
        while batch_w < args.target_batch:
            batch_w *= 2
        mean_entries = (on.get("fill_fraction") or 0.0) * batch_w
        if n > 1:
            check(
                mean_entries > 1.0,
                f"[{n} streams] coalescing ON actually batched "
                f"(mean {mean_entries:.1f} blocks/launch > OFF's 1)",
            )
        if n >= args.bat_gate_streams:
            check(
                level["speedup"] >= args.bat_min_speedup,
                f"[{n} streams] coalescing ON >= "
                f"{args.bat_min_speedup}x OFF admitted→verdict "
                f"throughput (measured {level['speedup']}x)",
            )
            check(
                (on.get("fill_fraction") or 0.0) >= 0.8,
                f"[{n} streams] batch fill fraction >= 0.8 "
                f"(measured {on.get('fill_fraction')})",
            )
            check(
                on["added_p99_ms"] <= args.max_batch_wait_ms,
                f"[{n} streams] p99 added latency "
                f"{on['added_p99_ms']}ms <= latency budget "
                f"{args.max_batch_wait_ms}ms",
            )
    return doc


# -- entry points ---------------------------------------------------------


def run_all(args, log, check) -> dict:
    doc: dict = {"tool": "bench_serve", "backend": "cpu"}
    doc["throughput"] = arm_throughput(args, log)
    check(
        doc["throughput"]["admitted_per_s"] >= args.min_rate,
        f"admitted rate {doc['throughput']['admitted_per_s']}/s >= "
        f"{args.min_rate}/s floor",
    )
    check(
        doc["throughput"]["verdicts"] == doc["throughput"]["histories"],
        "every admitted history produced a verdict (no silent drops "
        "behind the admission rate)",
    )
    doc["cache"] = arm_cache(args, log)
    check(doc["cache"]["speedup_ge_100x"],
          f"cache hit {doc['cache']['speedup']}x cheaper than a check")
    doc["chaos"] = arm_chaos(args, log, check)
    doc["saturation"] = arm_saturation(args, log, check)
    return doc


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--histories", type=int, default=12000,
                   help="throughput-arm one-shot submissions")
    p.add_argument("--base", type=int, default=16,
                   help="distinct synthesized histories in the corpus")
    p.add_argument("--ops", type=int, default=40,
                   help="op invocations per throughput history")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--seed", type=int, default=16)
    p.add_argument("--min-rate", type=float, default=10_000.0,
                   help="acceptance floor, admitted histories/s")
    p.add_argument("--cache-ops", type=int, default=4000,
                   help="cache-arm history size (the cold cost)")
    p.add_argument("--cache-reps", type=int, default=200)
    p.add_argument("--chaos-streams", type=int, default=6)
    p.add_argument("--chaos-ops", type=int, default=1200)
    p.add_argument("--chaos-blocks", type=int, default=8,
                   help="approximate blocks per chaos stream")
    p.add_argument("--kill-block", type=int, default=3,
                   help="worker 0 dies mid-feed of its Nth block")
    p.add_argument("--sat-submits", type=int, default=64)
    p.add_argument("--sat-block-delay", type=float, default=0.02,
                   help="per-block brake that forces the tiny ingress "
                   "queue to overflow")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--device", action="store_true", default=False,
                   help="per-block device dispatch in the carry engines "
                   "(chip runs; default CPU numpy twins)")
    p.add_argument("--batching", action="store_true", default=False,
                   help="run ONLY the continuous-batching arm "
                   "(ISSUE 20 evidence; both sub-arms use device "
                   "dispatch regardless of --device)")
    p.add_argument("--bat-streams", type=int, default=64,
                   help="top concurrency level for the batching arm")
    p.add_argument("--bat-blocks", type=int, default=100,
                   help="small-segment blocks per batching stream")
    p.add_argument("--bat-block-rows", type=int, default=64,
                   help="rows per batching-arm block (small segments)")
    p.add_argument("--target-batch", type=int, default=32,
                   help="coalescing target super-batch size")
    p.add_argument("--max-batch-wait-ms", type=float, default=25.0,
                   help="coalescing latency budget (dispatch deadline)")
    p.add_argument("--bat-min-speedup", type=float, default=2.0,
                   help="ON-vs-OFF throughput floor at the gate level")
    p.add_argument("--bat-probe-load", type=float, default=0.6,
                   help="latency-probe pace as a fraction of measured "
                   "ON capacity (below saturation: the sketch reads "
                   "scheduler hold time, not queueing)")
    p.add_argument("--bat-gate-streams", type=int, default=64,
                   help="apply the perf gates only at levels with at "
                   "least this many streams (tiny CI runs gate on "
                   "correctness, not speed)")
    p.add_argument("--out", default=None,
                   help="artifact dir (e.g. store/bench_r16_serve)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    out_dir = Path(args.out) if args.out else None
    log = _Log(out_dir / "bench_serve.log" if out_dir else None)
    failures: list[str] = []

    def check(cond: bool, msg: str) -> None:
        if cond:
            log(f"PASS  {msg}")
        else:
            failures.append(msg)
            log(f"FAIL  {msg}")

    t0 = time.perf_counter()
    if args.batching:
        doc = {"tool": "bench_serve", "backend": "cpu"}
        doc["serve_batching"] = run_batching(args, log, check)
    else:
        doc = run_all(args, log, check)
    doc["wall_s"] = round(time.perf_counter() - t0, 2)
    doc["pass"] = not failures
    doc["failures"] = failures
    doc["config"] = {k: v for k, v in vars(args).items() if k != "out"}
    if out_dir is not None:
        out_dir.mkdir(parents=True, exist_ok=True)
        (out_dir / "results.json").write_text(
            json.dumps(doc, indent=1) + "\n"
        )
        log(f"artifacts: {out_dir}/results.json + bench_serve.log")
    if failures:
        log(f"SERVE BENCH FAIL ({len(failures)} failed assertions)")
        return 1
    log("SERVE BENCH PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
