"""Cross-run report index for a store tree (ISSUE 11 tentpole (d)).

Walks ``store/``, renders any missing per-run report artifacts
(``report.html`` / ``timeline.html`` / ``forensics.html`` on invalid —
``jepsen_tpu/report/``), and emits ``store/index.html``: one row per
run with verdict, op count, latency headline (p50/p99 off the device
windowed-stats kernel), nemesis-window count, artifact links, and a
p50-latency trend sparkline across the runs — soak and fuzz campaigns
become a browsable surface instead of grep'd logs.

Same engine as ``jepsen-tpu report <store-dir>``; this wrapper exists
so campaign drivers (soak supervisors, fuzz loops) can regenerate the
index without the CLI's argv surface::

    python tools/report_store.py store/
    python tools/report_store.py store/ --no-render   # index-only
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# rendering must never hang on a wedged chip tunnel; the windowed-stats
# kernel is a tiny dispatch, fine on the CPU backend
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("store", help="store root to walk")
    p.add_argument(
        "--no-render",
        action="store_true",
        help="index only runs that already carry a report.json; "
        "render nothing new",
    )
    args = p.parse_args(argv)
    if not os.path.isdir(args.store):
        print(f"error: no such store dir {args.store}", file=sys.stderr)
        return 2

    from jepsen_tpu.report.index import build_store_index

    idx = build_store_index(
        args.store, render_missing=not args.no_render
    )
    if idx is None:
        print(f"no runs under {args.store}", file=sys.stderr)
        return 2
    print(str(idx))
    return 0


if __name__ == "__main__":
    sys.exit(main())
