"""Deterministic-window repro driver for the open r7 durable-queue
acked-loss (VERDICT #4 / PARITY index row for
``store/soak_r7_30min_5node_queue_red.txt``).

Replays the suspect fault window from the red soak — steady confirmed
enqueues while the cluster takes a partition, a membership
remove(+wipe)+rejoin, and a kill-with-durable-restart — directly against
the in-process ``ReplicatedBackend`` layer (no AMQP sockets), then heals
and drains.  A confirmed (acked) enqueue that is neither delivered nor
drained is a LOSS.

Usage::

    python tools/repro_r7_queue_loss.py --seeds 0 19   # sweep seeds 0..19

Exit 0 when no seed lost anything; 1 with a report when any did.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jepsen_tpu.harness.replication import ReplicatedBackend  # noqa: E402

FAST = dict(
    election_timeout=(0.15, 0.3),
    heartbeat_s=0.04,
    dead_owner_s=0.8,
    submit_timeout_s=2.0,
)

Q = "jepsen.queue"


class Cluster:
    """5 durable in-process nodes with kill/restart/forget/join/partition."""

    _next_port = [14000]

    @classmethod
    def _free_port(cls) -> int:
        """A listener port OUTSIDE the ephemeral range (16000-65535 on
        this image): kernel-assigned local ports of outbound RPC sockets
        must never collide with a Raft port we re-bind after a kill."""
        import socket

        while cls._next_port[0] < 16000:
            port = cls._next_port[0]
            cls._next_port[0] += 1
            try:
                with socket.socket() as s:
                    s.bind(("127.0.0.1", port))
                    return port
            except OSError:
                continue
        raise RuntimeError("no free low port")

    def __init__(self, root: str, n: int = 5, seed: int = 0):
        self.root = root
        self.names = [f"n{i}" for i in range(n)]
        self.peers: dict[str, tuple[str, int]] = {
            nm: ("127.0.0.1", self._free_port()) for nm in self.names
        }
        self.backends: dict[str, ReplicatedBackend] = {}
        for i, nm in enumerate(self.names):
            self.backends[nm] = ReplicatedBackend(
                nm, self.peers, data_dir=self._dir(nm),
                rng_seed=seed * 100 + i, **FAST,
            )
        self.blocked: set[frozenset] = set()

    def _dir(self, nm: str) -> str:
        return os.path.join(self.root, nm)

    def leader(self, timeout=25.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for nm, b in self.backends.items():
                if b is not None and b.raft.is_leader():
                    return nm
            time.sleep(0.02)
        raise AssertionError("no leader")

    def alive(self):
        return [nm for nm, b in self.backends.items() if b is not None]

    def kill(self, nm: str) -> None:
        b = self.backends[nm]
        if b is not None:
            b.stop()
        self.backends[nm] = None

    def restart(self, nm: str, fresh: bool = False) -> None:
        if fresh:
            shutil.rmtree(self._dir(nm), ignore_errors=True)
        for attempt in range(40):
            try:
                self.backends[nm] = ReplicatedBackend(
                    nm, {nm: self.peers[nm]} if fresh else self.peers,
                    data_dir=self._dir(nm), bootstrap=not fresh, **FAST,
                )
                break
            except OSError as e:  # lingering bind from a killed incarnation
                if attempt == 39:
                    print(
                        f"restart {nm} port {self.peers[nm][1]} stuck: {e}; "
                        f"alive={self.alive()}",
                        flush=True,
                    )
                    self.backends[nm] = None
                    return
                time.sleep(0.25)
        self._apply_blocks()

    def forget(self, nm: str, via: str) -> bool:
        return self.backends[via].raft.request_forget(nm, timeout_s=8.0)

    def join(self, nm: str, via: str) -> bool:
        return self.backends[nm].raft.request_join(
            self.peers[via], timeout_s=8.0
        )

    def partition(self, side_a: list[str], side_b: list[str]) -> None:
        for a in side_a:
            for b in side_b:
                self.blocked.add(frozenset((a, b)))
        self._apply_blocks()

    def heal(self) -> None:
        self.blocked.clear()
        for b in self.backends.values():
            if b is not None:
                b.raft.unblock_all()

    def _apply_blocks(self) -> None:
        for nm, b in self.backends.items():
            if b is None:
                continue
            b.raft.unblock_all()
            for link in self.blocked:
                if nm in link:
                    (other,) = link - {nm}
                    b.raft.block(other)

    def stop(self) -> None:
        for b in self.backends.values():
            if b is not None:
                b.stop()


def run_window(seed: int, minutes: float = 0.5) -> dict:
    import base64
    import random

    rng = random.Random(seed)
    root = tempfile.mkdtemp(prefix=f"repro_r7_{seed}_")
    c = Cluster(root, seed=seed)
    acked: list[int] = []
    seen: set[int] = set()
    stop = threading.Event()
    next_v = [0]

    def any_backend():
        alive = [b for b in c.backends.values() if b is not None]
        return rng.choice(alive) if alive else None

    c.leader()
    c.backends[c.names[0]].declare(Q, qtype="quorum")

    def publisher():
        while not stop.is_set():
            b = any_backend()
            if b is None:
                time.sleep(0.05)
                continue
            v = next_v[0]
            next_v[0] += 1
            try:
                if b.enqueue(Q, str(v).encode(), b""):
                    acked.append(v)
            except Exception:
                pass

    def consumer(i: int):
        while not stop.is_set():
            b = any_backend()
            if b is None:
                time.sleep(0.05)
                continue
            try:
                owner = f"{b.raft.name}|repro-c{i}"
                msg = b.dequeue(Q, owner)
                if msg is not None:
                    seen.add(int(msg.body.decode()))
                    b.settle(owner, msg.mid)
                else:
                    time.sleep(0.01)
            except Exception:
                time.sleep(0.02)

    threads = [threading.Thread(target=publisher, daemon=True)]
    threads += [
        threading.Thread(target=consumer, args=(i,), daemon=True)
        for i in range(2)
    ]
    for t in threads:
        t.start()

    t_end = time.monotonic() + minutes * 60.0
    events = []
    try:
        while time.monotonic() < t_end:
            # one churn cycle mirroring the red window:
            # partition -> heal -> remove+rejoin -> kill+restart
            names = list(c.names)
            rng.shuffle(names)
            side_a, side_b = names[:2], names[2:]
            c.partition(side_a, side_b)
            events.append(f"partition {side_a}|{side_b}")
            time.sleep(rng.uniform(0.5, 1.5))
            c.heal()

            victim = rng.choice([n for n in c.alive()])
            c.kill(victim)
            ok = False
            for via in c.alive():
                ok = c.forget(victim, via)
                if ok:
                    break
            events.append(f"forget {victim} ok={ok}")
            c.restart(victim, fresh=ok)
            if ok:
                joined = c.join(victim, rng.choice(
                    [n for n in c.alive() if n != victim]
                ))
                events.append(f"join {victim} ok={joined}")
            # kill another node mid-catch-up (the suspect moment)
            time.sleep(rng.uniform(0.0, 0.4))
            other = rng.choice([n for n in c.alive() if n != victim])
            c.kill(other)
            events.append(f"kill {other}")
            time.sleep(rng.uniform(0.2, 1.0))
            c.restart(other)
            events.append(f"restart {other}")
            time.sleep(rng.uniform(0.5, 1.0))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=2.0)
        c.heal()

    post: dict = {}
    # drain: every acked value must eventually be deliverable.  First
    # requeue every inflight owner — the harness has no broker-level
    # orphan sweep, so a committed-but-unreported DEQ (consumer submit
    # timed out, entry committed anyway) must not read as loss: the real
    # broker's sweeps requeue those within a tick.
    try:
        lead = c.leader(timeout=10.0)
        b = c.backends[lead]

        def sweep() -> int:
            # mirror the broker's continuous orphan sweep: re-propose
            # until the entries leave the inflight map (a submit lost to
            # an election window is retried, exactly like
            # broker._orphan_sweep_loop)
            with b.machine.lock:
                owners = {
                    o
                    for o, _q, _m in b.machine.inflight.values()
                    if not o.endswith("repro-drain")
                }
            for o in owners:
                b.requeue_owner(o)
            return len(owners)

        empties = 0
        deadline = time.monotonic() + 45.0
        while empties < 30 and time.monotonic() < deadline:
            sweep()
            owner = f"{lead}|repro-drain"
            msg = b.dequeue(Q, owner)
            if msg is None:
                empties += 1
                time.sleep(0.1)
                continue
            empties = 0
            seen.add(int(msg.body.decode()))
            b.settle(owner, msg.mid)
        # post-mortem evidence for any loss: is the enq still in the
        # committed log?  still inflight?  (distinguishes a Raft-level
        # committed-entry loss from a delivery-plane strand)
        lost_now = sorted(set(acked) - seen)
        post = {}
        if lost_now:
            with b.raft.lock:
                log = list(b.raft.log)
                commit = b.raft.commit_idx
            with b.machine.lock:
                inflight = {
                    int(m.body.decode())
                    for _o, _q, m in b.machine.inflight.values()
                }
                ready = {
                    int(m.body.decode())
                    for dq in b.machine.queues.values()
                    for m in dq
                }
            import base64 as _b64

            for v in lost_now:
                body = _b64.b64encode(str(v).encode()).decode()
                at = [
                    i + 1
                    for i, (_t, op) in enumerate(log)
                    if op.get("k") == "enq" and op.get("body") == body
                ]
                post[v] = {
                    "log_idx": at,
                    "committed": bool(at) and at[0] <= commit,
                    "inflight": v in inflight,
                    "ready": v in ready,
                }
    finally:
        c.stop()
        shutil.rmtree(root, ignore_errors=True)

    lost = sorted(set(acked) - seen)
    return {
        "seed": seed,
        "acked": len(acked),
        "seen": len(seen),
        "lost": lost,
        "post": post if lost else {},
        "events": events,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, nargs=2, default=[0, 9])
    p.add_argument("--minutes", type=float, default=0.5)
    args = p.parse_args()
    bad = 0
    for seed in range(args.seeds[0], args.seeds[1] + 1):
        r = run_window(seed, minutes=args.minutes)
        status = "LOST" if r["lost"] else "ok"
        print(
            f"seed {seed}: {status} acked={r['acked']} seen={r['seen']}"
            + (f" lost={r['lost'][:20]}{'...' if len(r['lost']) > 20 else ''}"
               if r["lost"] else ""),
            flush=True,
        )
        if r["lost"]:
            bad += 1
            print(f"  post-mortem: {r['post']}")
            for e in r["events"]:
                print(f"  {e}")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
