"""Adversarial matrix fuzzer: generated configs, triage, minimization,
and repro emission (ROADMAP direction 5 — the matrix machine).

Samples seeded random {workload x nemesis schedule x durability x
contract x cluster size x churn} configurations
(``jepsen_tpu/fuzz/space.py``), runs each under the matrix/_live triage
rules, and on any red:

1. confirms it on a fresh cluster (``--confirm`` runs),
2. greedily delta-debugs the schedule — nemesis events, then the op
   window — to the minimal failing window (``fuzz/minimize.py``),
3. emits a deterministic seeded repro driver into ``--emit-dir``
   (``store/fuzz_repro_<tag>.py``, the generated analogue of the
   hand-written ``tools/repro_r7_*`` pair).

Liveness proof (the red/green pair for the fuzzer itself)::

    # seeded bug: the fuzzer MUST find a red within the budget
    python tools/fuzz_matrix.py --seed 7 --budget 6 --db local \\
        --seed-bug ack-before-fsync --expect-red
    # same seed, no bug: the same schedules must come back green
    python tools/fuzz_matrix.py --seed 7 --budget 6 --db local

Exit codes: 0 = budget completed (with ``--expect-red``: a red was
found, minimized, and its repro emitted); 1 = ``--expect-red`` found
nothing, or a red was found while hunting (so CI-style callers notice
findings); 2 = usage.  ``--out`` captures the log fail-loud the way
``tools/soak.py`` does: the artifact lands only when the run reached
its expected ending.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import random
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _soak():
    """tools/soak.py (the fail-loud capture contract lives there)."""
    spec = importlib.util.spec_from_file_location(
        "soak", os.path.join(os.path.dirname(__file__), "soak.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_fuzz(args) -> int:
    import logging

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        stream=sys.stdout,
        force=True,
    )
    if args.quiet_cluster:
        for name in ("jepsen_tpu.runner", "jepsen_tpu.generator"):
            logging.getLogger(name).setLevel(logging.WARNING)

    from jepsen_tpu.fuzz.emit import emit_forensics, emit_repro
    from jepsen_tpu.fuzz.minimize import minimize
    from jepsen_tpu.fuzz.runner import is_red, triage_run
    from jepsen_tpu.fuzz.space import sample_config

    store = args.store or tempfile.mkdtemp(prefix="fuzz_matrix_")
    rng = random.Random(args.seed)
    sim_faults = (
        dict(f.split("=") for f in args.sim_fault) if args.sim_fault
        else None
    )
    print(
        f"# fuzz: seed={args.seed} budget={args.budget} db={args.db}"
        f"{' seed_bug=' + args.seed_bug if args.seed_bug else ''}"
        f"{' strict-contract' if args.strict_contract else ''}"
        f"{' sim_faults=' + str(sim_faults) if sim_faults else ''}",
        flush=True,
    )

    found = []
    t0 = time.monotonic()
    for i in range(args.budget):
        cfg = sample_config(
            rng,
            db=args.db,
            time_limit_s=args.time_limit,
            rate=args.rate,
            strict_contract=args.strict_contract,
            seed_bug=args.seed_bug,
            sim_faults=sim_faults,
            max_events=args.max_events,
            workload=args.workload,
        )
        print(f"# config {i + 1}/{args.budget}: {cfg.describe()}",
              flush=True)
        out = triage_run(cfg, store, attempts=args.attempts)
        print(f"# config {i + 1}: {out.status}"
              + (f" {out.invalidating}" if out.invalidating else "")
              + (f" {out.notes}" if out.notes else ""),
              flush=True)
        if out.status != "red":
            continue

        # confirm on a fresh cluster before any minting: a one-off
        # load artifact must not become a committed finding
        confirmed = all(
            is_red(cfg, store, attempts=args.attempts)
            for _ in range(max(0, args.confirm - 1))
        )
        if not confirmed:
            print(f"# config {i + 1}: red did NOT confirm — discarded "
                  f"as a load artifact (nothing emitted)", flush=True)
            continue

        print(f"# config {i + 1}: RED CONFIRMED — minimizing", flush=True)
        mincfg, stats = minimize(
            cfg,
            oracle=lambda c: is_red(c, store, attempts=args.attempts),
            confirm=args.confirm,
            log=lambda s: print(f"#   {s}", flush=True),
        )
        # the emitted spec must be the exact one just confirmed red —
        # re-run it once more to hold the outcome object for the emitter
        final = triage_run(mincfg, store, attempts=args.attempts)
        if final.status != "red":
            print("# minimized spec went flaky on the emission run — "
                  "emitting nothing (fail-loud)", flush=True)
            continue
        tag = f"s{args.seed}_c{cfg.seed}_{cfg.workload}"
        path = emit_repro(
            mincfg, final, args.emit_dir, tag, stats=stats,
            extra_summary=(
                f"Found by: tools/fuzz_matrix.py --seed {args.seed} "
                f"--db {args.db}"
                + (f" --seed-bug {args.seed_bug}" if args.seed_bug
                   else "")
                + (" --strict-contract" if args.strict_contract else "")
            ),
        )
        print(f"# config {i + 1}: minimized "
              f"({stats.events_before}->{stats.events_after} events, "
              f"{stats.window_before:g}->{stats.window_after:g}s window, "
              f"{stats.runs} runs) — repro emitted: {path}", flush=True)
        forensics = emit_forensics(final, path)
        if forensics:
            print(f"# config {i + 1}: forensics page: {forensics}",
                  flush=True)
        # evidence-level shrink (fleet memory): the recorded red's
        # minimal op window, every re-confirmation CHECK routed through
        # the store's prefix-checkpoint index so tail-trim probes pay
        # for their unshared tails, not whole histories
        hist = (
            os.path.join(str(final.run_dir), "history.jsonl")
            if final.run_dir else None
        )
        if hist and os.path.isfile(hist):
            try:
                from jepsen_tpu.fuzz.minimize import minimize_recorded

                rs = minimize_recorded(
                    hist,
                    os.path.join(store, "shrink_replay"),
                    prefix_index=os.path.join(store, "ckpt_index"),
                    confirm=args.confirm,
                    log=lambda s: print(f"#   {s}", flush=True),
                )
                print(
                    f"# config {i + 1}: recorded window "
                    f"{rs.n_ops} -> {rs.min_red_ops} ops "
                    f"({len(rs.probes)} probes, "
                    f"{rs.resumed_probes} prefix-resumed, "
                    f"{rs.wall_s:.2f}s)", flush=True,
                )
            except ValueError as e:
                # a red whose invalidity needs the FULL history (e.g.
                # end-state loss) has no smaller window — report, keep
                print(f"# config {i + 1}: recorded-window shrink "
                      f"skipped: {e}", flush=True)
        # matrix auto-grow: the minimized red becomes a pinned row the
        # static matrix replays (deduped by finding identity, so a
        # re-found red bumps the existing row instead of multiplying)
        if args.pins_dir:
            from jepsen_tpu.fuzz.pins import append_pin

            ppath, added = append_pin(
                args.pins_dir, mincfg.to_spec(), final.invalidating,
                source=f"fuzz_matrix --seed {args.seed} c{cfg.seed}",
            )
            print(f"# config {i + 1}: "
                  f"{'pinned' if added else 're-found pin bumped'} in "
                  f"{ppath}", flush=True)
        found.append({
            "forensics": forensics,
            "config_seed": cfg.seed,
            "workload": cfg.workload,
            "invalidating": final.invalidating,
            "repro": path,
            "events": [e.to_json() for e in mincfg.events],
            "window_s": mincfg.opts["time-limit"],
        })
        if args.stop_after_red:
            break

    wall = time.monotonic() - t0
    print(f"# fuzz done: {len(found)} red(s) in {wall:.0f}s wall")
    print(json.dumps({"found": found}, indent=1, default=str))
    if args.expect_red:
        if not found:
            print("# FAIL: --expect-red but the budget found no red "
                  "(the seeded bug went uncaught)", file=sys.stderr)
            return 1
        return 0
    # hunting mode: findings are a non-zero exit so CI notices
    return 1 if found else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, required=True,
                   help="fuzzer seed: the entire config stream is a "
                        "pure function of it")
    p.add_argument("--budget", type=int, default=10,
                   help="number of configs to sample and run")
    p.add_argument("--db", choices=("local", "sim"), default="local",
                   help="target harness: local broker processes "
                        "(full fault space) or the in-process sim "
                        "(partition/kill/pause only; CI smoke)")
    p.add_argument("--time-limit", type=float, default=None,
                   help="pin every config's load window (default: "
                        "sampled 8-20s)")
    p.add_argument("--rate", type=float, default=None,
                   help="pin the op rate (default: sampled)")
    p.add_argument("--max-events", type=int, default=6,
                   help="max nemesis events per schedule")
    p.add_argument("--workload", default=None,
                   choices=("queue", "stream", "elle", "mutex"),
                   help="pin the workload family (default: sampled "
                        "per config)")
    p.add_argument("--attempts", type=int, default=2,
                   help="triage attempts per run (undecided retries)")
    p.add_argument("--confirm", type=int, default=2,
                   help="fresh-cluster confirmations a red (and every "
                        "accepted shrink) needs before it counts")
    p.add_argument("--seed-bug",
                   choices=("confirm-before-quorum",
                            "drop-unacked-on-close",
                            "ack-before-fsync", "no-wire-checksum"),
                   default=None,
                   help="(--db local) inject a known bug into every "
                        "sampled config — the fuzzer-liveness mode: "
                        "it MUST find and minimize a red")
    p.add_argument("--sim-fault", action="append", default=None,
                   metavar="KNOB=N",
                   help="(--db sim) seeded sim fault, e.g. "
                        "drop_acked_every=5 (repeatable)")
    p.add_argument("--strict-contract", action="store_true",
                   help="sample contracts TIGHTER than the SUT claims "
                        "(exactly-once on the at-least-once live "
                        "queue, serializable elle) — the relaxed-"
                        "contract red class")
    p.add_argument("--expect-red", action="store_true",
                   help="exit non-zero unless a red was found, "
                        "minimized, and emitted (pair with --seed-bug)")
    p.add_argument("--stop-after-red", action="store_true",
                   help="stop the budget after the first confirmed red")
    p.add_argument("--emit-dir", default="store",
                   help="where minimized repro drivers land")
    p.add_argument("--pins-dir", default="store",
                   help="where the auto-grown regression corpus "
                        "(fuzz_pins.json) lives; every confirmed-"
                        "minimized red is appended as a pinned row "
                        "the static matrix replays (empty string "
                        "disables pinning)")
    p.add_argument("--store", default=None,
                   help="run-store root (default: a temp dir)")
    p.add_argument("--quiet-cluster", action="store_true",
                   help="suppress per-op runner logging")
    p.add_argument("--out", default=None,
                   help="evidence file for the fuzzer log; captured "
                        "fail-loud (only on the expected ending)")
    args = p.parse_args(argv)
    if args.seed_bug and args.db != "local":
        p.error("--seed-bug needs --db local (the sim injects faults "
                "via --sim-fault instead)")
    if args.sim_fault and args.db != "sim":
        p.error("--sim-fault is a --db sim knob")
    if args.out is None:
        return run_fuzz(args)
    return _soak().capture(args.out, lambda: run_fuzz(args))


if __name__ == "__main__":
    sys.exit(main())
