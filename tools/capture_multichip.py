"""Multi-chip readiness capture (VERDICT r4 next-step #7).

``dryrun_multichip`` proves the sharding *structure* on a virtual CPU
mesh; this tool is the inverse of the chip watcher for REAL meshes: run
it whenever a backend with ``n_devices > 1`` appears (bench.py invokes
it automatically after its headline when the device count allows), and
it executes every sharded checker family — queue (total-queue +
queue-lin over hist×seq with psum/pmin combines), stream (seq-parallel
scan with the boundary ppermute), elle (hist-parallel MXU closure), and
mutex (hist-parallel WGL frontier search) — on the real device mesh,
recording a provenance-stamped ``MULTICHIP_DETAILS.json``.

On a single-device backend it prints a one-line skip record (the watch
log's proof that no multi-chip window opened) and exits 0.

Reference tie-in: the capability twin of running the reference's suite
against its 5-worker AWS topology (``ci/rabbitmq-jepsen-aws.tf``) —
the sharded checkers are this framework's scale story (SURVEY.md §2.4).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_PATH = os.path.join(REPO, "MULTICHIP_DETAILS.json")


def capture(out_path: str = OUT_PATH) -> dict:
    sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp

    from jepsen_tpu.utils.jaxenv import ensure_backend

    backend = ensure_backend()
    n = jax.device_count()
    base: dict = {
        "backend": backend,
        "n_devices": n,
    }
    if n < 2:
        return {**base, "skipped": True,
                "reason": "single-device backend — no multi-chip window"}

    from jepsen_tpu.checkers.elle import infer_txn_graph, pack_txn_graphs
    from jepsen_tpu.checkers.stream_lin import pack_stream_histories
    from jepsen_tpu.checkers.wgl import mutex_wgl_ops, pack_wgl_batch
    from jepsen_tpu.history.encode import pack_histories
    from jepsen_tpu.history.synth import (
        ElleSynthSpec,
        MutexSynthSpec,
        StreamSynthSpec,
        SynthSpec,
        synth_batch,
        synth_elle_batch,
        synth_mutex_batch,
        synth_stream_batch,
    )
    from jepsen_tpu.models.core import OwnedMutex
    from jepsen_tpu.parallel import (
        checker_mesh,
        shard_packed,
        sharded_elle,
        sharded_stream_lin,
        sharded_total_queue,
        sharded_queue_lin,
        sharded_wgl,
    )

    seq = 2 if n % 2 == 0 else 1
    mesh = checker_mesh(seq=seq)
    hist = mesh.shape["hist"]
    B = 8 * hist  # a few histories per device — readiness, not a bench
    base["mesh"] = {k: int(v) for k, v in mesh.shape.items()}
    families: dict = {}

    def timed(name, fn):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        compile_and_run_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        run_s = time.perf_counter() - t1
        return out, {
            "compile_and_first_run_s": round(compile_and_run_s, 2),
            "steady_run_ms": round(run_s * 1e3, 2),
        }

    # queue family
    packed = shard_packed(
        pack_histories(
            [sh.ops for sh in synth_batch(B, SynthSpec(n_ops=120))],
            length=512 * seq,
        ),
        mesh,
    )
    (tq, ql), stats = timed(
        "queue", lambda: (
            sharded_total_queue(packed, mesh),
            sharded_queue_lin(packed, mesh),
        )
    )
    families["queue"] = {
        **stats,
        "batch": B,
        "valid_all": bool(jnp.asarray(tq.valid).all()
                          & jnp.asarray(ql.valid).all()),
    }

    # stream family (seq-parallel when seq > 1)
    sbatch = pack_stream_histories(
        [sh.ops for sh in synth_stream_batch(B, StreamSynthSpec(n_ops=96))]
    )
    sl, stats = timed("stream", lambda: sharded_stream_lin(sbatch, mesh))
    families["stream"] = {
        **stats, "batch": B,
        "valid_all": bool(jnp.asarray(sl.valid).all()),
    }

    # elle family — with the ISSUE-18 packed multi-chip closure: on a
    # seq mesh the default path packs the adjacency to uint32 bitplanes
    # and shards the plane axis across devices; the capture records
    # whether that path actually lowered or honestly fell back to dense
    from jepsen_tpu.obs.metrics import REGISTRY

    ebatch = pack_txn_graphs(
        [
            infer_txn_graph(sh.ops)
            for sh in synth_elle_batch(B, ElleSynthSpec(n_txns=32))
        ]
    )
    fb0 = REGISTRY.counter("mesh.closure_dense_fallbacks").value
    el, stats = timed("elle", lambda: sharded_elle(ebatch, mesh))
    fb1 = REGISTRY.counter("mesh.closure_dense_fallbacks").value
    families["elle"] = {
        **stats, "batch": B,
        "valid_all": bool(jnp.asarray(el.valid).all()),
        "closure": (
            "hist-sharded" if seq == 1
            else ("packed-sharded" if fb1 == fb0 else "dense-fallback")
        ),
        "dense_fallbacks": int(fb1 - fb0),
    }

    # mutex family (WGL frontier search)
    mbatch = pack_wgl_batch(
        [
            mutex_wgl_ops(sh.ops)
            for sh in synth_mutex_batch(B, MutexSynthSpec(n_ops=24))
        ]
    )
    (m_ok, m_ovf), stats = timed(
        "mutex", lambda: sharded_wgl(mbatch, mesh, (OwnedMutex, ()))
    )
    families["mutex"] = {
        **stats, "batch": B,
        "valid_all": bool(
            jnp.asarray(m_ok).all() & ~jnp.asarray(m_ovf).any()
        ),
    }

    # scale-out pipeline: the SAME harness the CPU scaling bench runs
    # (per-device input lanes + meshed dispatch + collective verdict
    # reduction, bytes-to-verdict from files, caches off) on the REAL
    # mesh — armed so the moment a multi-chip tunnel window opens, the
    # capture records the scaled end-to-end numbers, not just the
    # per-program readiness rows above
    import tempfile

    from jepsen_tpu.history.store import write_history_jsonl
    from jepsen_tpu.parallel.pipeline import check_sources

    scaleout: dict = {"lanes": n, "mode": "mesh + lanes + reduce"}
    with tempfile.TemporaryDirectory() as td:
        for fam, synth_base in (
            (
                "stream",
                synth_stream_batch(B, StreamSynthSpec(n_ops=96), lost=1),
            ),
            (
                "elle",
                synth_elle_batch(B, ElleSynthSpec(n_txns=32), g2_cycle=1),
            ),
        ):
            paths = []
            for i, sh in enumerate(synth_base):
                p = os.path.join(td, f"{fam}{i:03d}.jsonl")
                write_history_jsonl(p, sh.ops)
                paths.append(p)
            kw = dict(
                chunk=max(8, B // 4), mesh=mesh, lanes=0, reduce=True,
                use_cache=False,
                # a captured artifact must never carry a partially-
                # judged corpus — crash loud rather than quarantine
                fail_fast=True,
            )
            check_sources(fam, paths, **kw)  # warm the jitted programs
            t0 = time.perf_counter()
            verdict, stats = check_sources(fam, paths, **kw)
            wall = time.perf_counter() - t0
            scaleout[fam] = {
                "e2e_histories_per_sec": round(len(paths) / wall, 1),
                "histories": len(paths),
                "invalid": verdict["invalid"],
                "device_idle_frac": round(stats.device_idle_frac, 3),
                "lanes": stats.lanes,
            }
    families["pipeline_scaleout"] = scaleout

    # ISSUE 18: the TRUE global mesh — a 2-process fleet joined into
    # one jax.distributed mesh over this backend, the collective
    # verdict program's all_gather/psum crossing the host boundary.
    # The outcome is recorded either way (a single tunneled chip cannot
    # host two cooperating processes — that refusal is itself the
    # PARITY evidence until a real multi-host window opens).
    from jepsen_tpu.parallel.distributed import run_multiprocess_check

    gm: dict = {"procs": 2, "seq": seq, "workload": "elle"}
    with tempfile.TemporaryDirectory() as td:
        paths = []
        for i, sh in enumerate(
            synth_elle_batch(B, ElleSynthSpec(n_txns=32), g2_cycle=1)
        ):
            p = os.path.join(td, f"gm{i:03d}.jsonl")
            write_history_jsonl(p, sh.ops)
            paths.append(p)
        try:
            t0 = time.perf_counter()
            verdict, info = run_multiprocess_check(
                "elle", paths, 2, devices_per_proc=max(1, n // 2),
                chunk=max(8, B // 4), reduce=True, global_mesh=True,
                seq=seq, timeout_s=600.0, platform=backend,
            )
            gm.update(
                wall_s=round(time.perf_counter() - t0, 2),
                verdict=verdict,
                degraded=info["degraded"],
                ok=True,
            )
        except Exception as e:  # noqa: BLE001 - recorded, not raised
            gm.update(ok=False, error=f"{type(e).__name__}: {e}")
    families["global_mesh"] = gm

    out = {**base, "skipped": False, "families": families}

    # provenance: same evidence block shape as BENCH_DETAILS.json
    from jepsen_tpu.utils.harvest import _head_rev

    prov = {
        "timestamp_utc": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
    }
    try:
        prov["device_kind"] = jax.devices()[0].device_kind
    except Exception as e:  # noqa: BLE001 - evidence only
        prov["device_kind"] = f"unknown ({type(e).__name__})"
    prov["git_rev"] = _head_rev(REPO) or "unknown"
    out["provenance"] = prov

    # a CPU-mesh run (e.g. the virtual-device mechanism test) must never
    # clobber a real chip-mesh capture — same rule as BENCH_DETAILS.json —
    # and must never land on the DEFAULT artifact path at all: a
    # cpu-backend file under the multichip-evidence filename is one
    # `git add -A` away from shipping virtual-mesh numbers as chip
    # evidence (tests pass an explicit tmp out_path)
    if backend != "tpu":
        if os.path.abspath(out_path) == os.path.abspath(OUT_PATH):
            out["not_written"] = (
                "cpu capture refused at the default artifact path"
            )
            return out
        try:
            with open(out_path) as fh:
                if json.load(fh).get("backend") == "tpu":
                    out["not_written"] = "existing tpu capture kept"
                    return out
        except (OSError, ValueError):
            pass
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def main() -> int:
    out = capture()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
