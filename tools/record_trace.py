"""Record the ISSUE-10 flight-recorder evidence artifact: ONE Perfetto
trace holding both timelines red triage needs side by side —

1. **Live phase**: a short mixed-nemesis durable queue run on the real
   local cluster (the soak recipe at compressed duration), under the
   ``tests/_live.py`` triage rules.  The runner's instrumentation puts
   every fault window on the ``nemesis`` track and the run phases
   (setup / load / teardown / analysis) on the ``run`` track; the
   pipelined post-run analysis (``attach_pipelined_checkers``) already
   emits produce/place/check stage spans for the run's own history.
2. **North-star phase**: the full BASELINE.json #1 config (10k ×
   ~1000-op-row histories, bytes → verdict) through the meshed
   multi-lane reduced pipeline — the PR-5 north-star run, now visible
   as per-lane stage spans plus mesh collective-dispatch spans.

Fail-loud capture discipline (tools/soak.py's rule): the artifact is
written ONLY when the live phase reached its expected verdict, the
north-star check completed, AND the ring actually holds both
nemesis-window and pipeline-stage spans — anything else exits non-zero
with no artifact.

Recipe for the committed artifact (2-core CPU container, 8 virtual
devices — the same shape the north_star bench section pins)::

    python tools/record_trace.py --out store/trace_r9_northstar_nemesis.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    # the v5e-8 mesh shape the north-star target names (bench.py's
    # section discipline); must land before jax initializes
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )


def _live_phase(args) -> None:
    """The soak recipe at compressed duration: mixed nemesis, durable
    queue, pipelined analysis, triage to the expected-green verdict."""
    from _live import run_live_with_triage

    from jepsen_tpu.client import native as native_mod
    from jepsen_tpu.harness.localcluster import build_local_test
    from jepsen_tpu.parallel.pipeline import attach_pipelined_checkers

    opts = {
        "rate": args.rate,
        "time-limit": args.live_seconds,
        "time-before-partition": 2.0,
        "partition-duration": 5.0,
        "network-partition": "partition-random-halves",
        "nemesis": "mixed",
        "recovery-sleep": 8.0,
        "publish-confirm-timeout": 5.0,
        "durable": True,
        "seed": args.seed,
    }

    def build():
        native_mod.reset()
        test, transport = build_local_test(
            opts,
            n_nodes=args.nodes,
            concurrency=args.nodes,
            checker_backend="cpu",
            store_root=args.store,
            workload="queue",
            durable=True,
        )
        attach_pipelined_checkers(test, "queue")
        return test, transport

    run = run_live_with_triage(build, expect="valid", max_attempts=2)
    print(
        f"# live phase: {len(run.history)} history ops, "
        f"valid?={run.results.get('valid?')}",
        flush=True,
    )


def _north_star_phase(args) -> None:
    from jepsen_tpu.history.store import write_history_jsonl
    from jepsen_tpu.history.synth import SynthSpec, synth_batch
    from jepsen_tpu.parallel.mesh import checker_mesh
    from jepsen_tpu.parallel.pipeline import check_sources

    base = synth_batch(
        args.base_n, SynthSpec(n_ops=args.n_ops, n_processes=5), lost=1
    )
    with tempfile.TemporaryDirectory() as td:
        files = []
        for i, sh in enumerate(base):
            p = os.path.join(td, f"h{i}.jsonl")
            write_history_jsonl(p, sh.ops)
            files.append(p)
        reps = (args.histories + args.base_n - 1) // args.base_n
        srcs = (files * reps)[: args.histories]
        t0 = time.perf_counter()
        verdict, stats = check_sources(
            "queue", srcs, chunk=args.chunk, mesh=checker_mesh(), lanes=0,
            reduce=True, use_cache=False,
            # a recorded artifact must never carry a partially-judged
            # corpus — crash loud rather than quarantine
            fail_fast=True,
        )
        wall = time.perf_counter() - t0
    print(
        f"# north-star phase: {args.histories} histories bytes->verdict "
        f"in {wall:.1f}s over {stats.lanes} lanes "
        f"(invalid={verdict['invalid']})",
        flush=True,
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", required=True)
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument("--live-seconds", type=float, default=25.0)
    p.add_argument("--rate", type=float, default=40.0)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--histories", type=int, default=10_000)
    p.add_argument("--base-n", type=int, default=128)
    p.add_argument("--n-ops", type=int, default=470)
    p.add_argument("--chunk", type=int, default=256)
    p.add_argument("--store", default=None,
                   help="live-phase store root (default: a temp dir)")
    args = p.parse_args(argv)
    if args.store is None:
        args.store = tempfile.mkdtemp(prefix="record_trace_")

    from jepsen_tpu.obs import export as obs_export
    from jepsen_tpu.obs import trace as obs_trace

    obs_trace.enable(capacity=1 << 18)
    try:
        with obs_trace.span("phase.live", track="phases"):
            _live_phase(args)
        with obs_trace.span("phase.north_star_check", track="phases"):
            _north_star_phase(args)
    except BaseException as e:
        print(
            f"# NO artifact: run did not complete "
            f"({type(e).__name__}: {e})",
            flush=True,
        )
        raise
    finally:
        obs_trace.disable()

    recs = obs_trace.snapshot()
    nemesis = sum(
        1 for r in recs if r[0] == "X" and str(r[1]).startswith("nemesis:")
    )
    pipeline = sum(
        1 for r in recs if r[0] == "X" and str(r[1]).startswith("pipeline.")
    )
    if not nemesis or not pipeline:
        print(
            f"# NO artifact: ring holds {nemesis} nemesis-window and "
            f"{pipeline} pipeline-stage spans — both must be visible "
            f"(the artifact's whole claim)",
            flush=True,
        )
        return 1
    summary = obs_export.write_trace(args.out)
    summary["nemesis_window_spans"] = nemesis
    summary["pipeline_stage_spans"] = pipeline
    print(f"# trace artifact: {json.dumps(summary)}", flush=True)
    print(
        "# open at https://ui.perfetto.dev — nemesis windows overlay "
        "the lane/stage work",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
