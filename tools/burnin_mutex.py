"""Mutex-family burn-in driver: N-minute mixed-nemesis soaks under the
``tests/_live.py`` triage harness.

The round-5 burn-ins proved the unfenced single-token lock double-grants
under kill/pause revocation (``store/burnin_r5_10min_5node_mutex_*_red``)
— the detection half of the lock story.  This driver produces the other
half: with ``--fenced``, the SAME 5-node mixed-nemesis revocation
schedule (same ``--seed`` → same nemesis family picks and victims) must
soak GREEN, because grants carry Raft-commit-index fencing tokens, the
broker rejects superseded tokens, and the checker verifies token order
instead of hold exclusivity.

Run both twins with one seed and tee into ``store/``::

    python tools/burnin_mutex.py --minutes 10 --seed 7 \
        2>&1 | tee store/burnin_r6_10min_5node_mutex_unfenced_red.txt
    python tools/burnin_mutex.py --minutes 10 --seed 7 --fenced \
        2>&1 | tee store/burnin_r6_10min_5node_mutex_fenced_green.txt

Exit code 0 = the run reached its expected verdict (invalid for
unfenced — the documented hazard — valid for fenced) under the triage
rules; non-zero = it never did within ``--attempts``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--minutes", type=float, default=10.0)
    p.add_argument("--nodes", type=int, default=5)
    p.add_argument("--seed", type=int, default=7,
                   help="nemesis schedule seed — the SAME seed drives the "
                        "same revocation schedule for both twins")
    p.add_argument("--rate", type=float, default=40.0)
    p.add_argument("--fenced", action="store_true")
    p.add_argument("--attempts", type=int, default=2,
                   help="triage attempts (fresh cluster each)")
    p.add_argument("--store", default=None,
                   help="store root (default: a temp dir)")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        stream=sys.stdout,
    )

    from _live import run_live_with_triage

    from jepsen_tpu.checkers.live import attach_live_monitor_for
    from jepsen_tpu.client import native as native_mod
    from jepsen_tpu.harness.localcluster import build_local_test
    from jepsen_tpu.history.store import _json_default

    store = args.store or tempfile.mkdtemp(prefix="burnin_mutex_")
    opts = {
        "rate": args.rate,
        "time-limit": args.minutes * 60.0,
        "time-before-partition": 2.0,
        "partition-duration": 10.0,
        "network-partition": "partition-random-halves",
        "nemesis": "mixed",
        "recovery-sleep": 20.0,
        "publish-confirm-timeout": 5.0,
        "durable": True,
        "seed": args.seed,
        "fenced": args.fenced,
    }
    mode = "fenced" if args.fenced else "unfenced"
    expect = "valid" if args.fenced else "invalid"
    print(
        f"# mutex burn-in: {mode}, {args.nodes} nodes, "
        f"{args.minutes:g} min mixed nemesis, seed={args.seed}, "
        f"expect={expect}", flush=True,
    )

    monitors = []

    def build():
        native_mod.reset()
        test, transport = build_local_test(
            opts,
            n_nodes=args.nodes,
            concurrency=args.nodes,
            checker_backend="cpu",
            store_root=store,
            workload="mutex",
            durable=True,
        )
        m = attach_live_monitor_for(
            test, "fenced-mutex" if args.fenced else "mutex"
        )
        monitors.append(m)
        return test, transport

    t0 = time.monotonic()
    try:
        run = run_live_with_triage(
            build, expect=expect, max_attempts=args.attempts
        )
    except AssertionError as e:
        print(f"# burn-in FAILED to reach expect={expect}: {e}", flush=True)
        return 1
    wall = time.monotonic() - t0
    if monitors and monitors[-1] is not None:
        snap = monitors[-1].snapshot()
        counts = ", ".join(
            f"{v} {k}" for k, v in snap["anomalies"].items()
        )
        print(
            f"# live monitor ({monitors[-1].name}): {counts} "
            f"(of {snap['observations']} observations); "
            f"violation-so-far={snap['violation-so-far']}", flush=True,
        )
    print(json.dumps(run.results, indent=1, default=_json_default))
    print(
        f"# burn-in done in {wall:.0f}s wall ({len(run.history)} history "
        f"ops, attempts logged above)", flush=True,
    )
    verdict = run.results.get("valid?")
    if verdict is True:
        print("Everything looks good! ヽ('ー`)ノ")
    else:
        print("Analysis invalid! ಠ~ಠ")
    # the run reached the EXPECTED verdict (triage guarantees this)
    return 0


if __name__ == "__main__":
    sys.exit(main())
