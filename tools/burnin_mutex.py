"""Mutex-family burn-in driver: N-minute mixed-nemesis soaks under the
``tests/_live.py`` triage harness.

The round-5 burn-ins proved the unfenced single-token lock double-grants
under kill/pause revocation (``store/burnin_r5_10min_5node_mutex_*_red``)
— the detection half of the lock story.  This driver produces the other
half: with ``--fenced``, the SAME 5-node mixed-nemesis revocation
schedule (same ``--seed`` → same nemesis family picks and victims) must
soak GREEN, because grants carry Raft-commit-index fencing tokens, the
broker rejects superseded tokens, and the checker verifies token order
instead of hold exclusivity.

Since r7 this is a thin wrapper over ``tools/soak.py`` (one shared run
body; the mutex expectation wired in: unfenced expects *invalid* — the
documented hazard — fenced expects *valid*).  Capture evidence with
``--out``, never with ``tee``: the artifact only lands when the run
reaches its expected verdict; a failed invocation exits non-zero and
leaves ``OUT.failed``::

    python tools/burnin_mutex.py --minutes 10 --seed 7 \
        --out store/burnin_r6_10min_5node_mutex_unfenced_red.txt
    python tools/burnin_mutex.py --minutes 10 --seed 7 --fenced \
        --out store/burnin_r6_10min_5node_mutex_fenced_green.txt

Exit code 0 = the run reached its expected verdict within
``--attempts``; non-zero = it never did, and no artifact was written.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import soak  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--minutes", type=float, default=10.0)
    p.add_argument("--nodes", type=int, default=5)
    p.add_argument("--seed", type=int, default=7,
                   help="nemesis schedule seed — the SAME seed drives the "
                        "same revocation schedule for both twins")
    p.add_argument("--rate", type=float, default=40.0)
    p.add_argument("--fenced", action="store_true")
    p.add_argument("--attempts", type=int, default=2,
                   help="triage attempts (fresh cluster each)")
    p.add_argument("--store", default=None,
                   help="store root (default: a temp dir)")
    p.add_argument("--out", default=None,
                   help="evidence file; only written when the run "
                        "reaches its expected verdict")
    args = p.parse_args(argv)

    # translate to soak.py's OWN argv surface (one argument parser, no
    # hand-built Namespace to drift when the driver grows options)
    soak_argv = [
        "--workload", "mutex",
        "--minutes", str(args.minutes),
        "--nodes", str(args.nodes),
        "--seed", str(args.seed),
        "--rate", str(args.rate),
        "--expect", "valid" if args.fenced else "invalid",
        "--attempts", str(args.attempts),
    ]
    if args.store is not None:
        soak_argv += ["--store", args.store]
    if args.fenced:
        soak_argv.append("--fenced")
    if args.out is not None:
        soak_argv += ["--out", args.out]
    return soak.main(soak_argv)


if __name__ == "__main__":
    sys.exit(main())
